"""Autopilot control laws: pure functions from signals + state to actions.

Each law is a pure function of (aggregated signals, mutable per-key state
dict, bounds, now) — no clocks, no RPC, no metrics — so the laws unit-test
with a fake clock and run inside the controller under a distsan hot-path
tag without ever touching the control plane. The caller (Autopilot.tick)
owns persistence of the state dicts and actuation of the returned actions.

The control-law table (signal → condition → action → cooldown) is
documented in docs/autoscale.md and must stay in sync with this module.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class ReplicaBounds:
    """Per-deployment scaling bounds + timing knobs, resolved once per tick
    from the deployment's AutoscalingConfig (when set) or the
    serve_autopilot_* flags."""

    min_replicas: int = 1
    max_replicas: int = 8
    burn_high: float = 1.0
    queue_high: float = 8.0
    sustain_ticks: int = 2
    upscale_cooldown_s: float = 5.0
    downscale_cooldown_s: float = 30.0
    cold_start_guard_s: float = 60.0


@dataclass(frozen=True)
class WeightBounds:
    step: float = 0.25
    floor: float = 0.25
    ceiling: float = 8.0
    deadband: float = 0.25
    sustain_ticks: int = 2
    cooldown_s: float = 5.0


def new_replica_state(target: int) -> dict:
    """Fresh per-deployment law state. Wall-clock timestamps (time.time)
    so persisted cooldowns survive a controller restart."""
    return {
        "target": int(target),
        "hot_ticks": 0,
        "idle_ticks": 0,
        "last_up_t": 0.0,
        "last_down_t": 0.0,
        "woken_t": 0.0,
    }


def replica_law(
    *,
    state: dict,
    replicas: int,
    queued: float,
    ongoing: float,
    burn: float,
    bounds: ReplicaBounds,
    now: float,
) -> Optional[Tuple[int, str, dict]]:
    """Replica-count law. Mutates `state` tick counters; returns
    (new_target, rule, detail) when an action fires, else None.

    Up: burn-rate or per-replica queue pressure sustained for
    `sustain_ticks`, after the upscale cooldown. The step is proportional
    to queue overload (a 3x rate step should not climb one replica per
    cooldown) but always bounded by max_replicas.
    Down: zero queue, zero in-flight, and burn comfortably inside budget
    sustained for 2x `sustain_ticks`, after the (long) downscale cooldown —
    one replica at a time, and down to zero only outside the cold-start
    guard window.
    """
    target = state["target"]
    per_replica_q = queued / max(1, replicas)
    hot = burn >= bounds.burn_high or per_replica_q >= bounds.queue_high
    idle = burn < 0.5 * bounds.burn_high and queued <= 0 and ongoing <= 0
    state["hot_ticks"] = state["hot_ticks"] + 1 if hot else 0
    state["idle_ticks"] = state["idle_ticks"] + 1 if idle else 0

    if (
        hot
        and target < bounds.max_replicas
        and state["hot_ticks"] >= bounds.sustain_ticks
        and now - state["last_up_t"] >= bounds.upscale_cooldown_s
    ):
        # Queue-proportional step: enough replicas that the CURRENT queue
        # would sit at ~queue_high per replica, at least +1.
        step = max(1, math.ceil(queued / max(bounds.queue_high, 1.0)) - target)
        new = min(bounds.max_replicas, target + step)
        state["target"] = new
        state["last_up_t"] = now
        state["hot_ticks"] = 0
        return new, "replica_up", {
            "burn": round(burn, 3), "queued": queued,
            "per_replica_queue": round(per_replica_q, 2), "from": target,
        }

    floor = bounds.min_replicas
    if floor == 0 and now - state["woken_t"] < bounds.cold_start_guard_s:
        floor = max(floor, 1)  # cold-start guard: no re-zero right after a wake
    if (
        idle
        and target > floor
        and state["idle_ticks"] >= 2 * bounds.sustain_ticks
        and now - state["last_down_t"] >= bounds.downscale_cooldown_s
    ):
        new = target - 1
        state["target"] = new
        state["last_down_t"] = now
        state["idle_ticks"] = 0
        return new, "replica_down", {
            "burn": round(burn, 3), "queued": queued, "from": target,
        }
    return None


def wake_law(*, state: dict, bounds: ReplicaBounds, now: float,
             ) -> Optional[Tuple[int, str, dict]]:
    """Scale-to-zero wake: a routed request found ZERO replicas. Bypasses
    pressure hysteresis and cooldowns by design — the requester is already
    waiting — and arms the cold-start guard so the idle law cannot retire
    the fresh replica straight back to zero."""
    if state["target"] >= 1:
        return None
    state["target"] = 1
    state["woken_t"] = now
    state["idle_ticks"] = 0
    return 1, "cold_start_wake", {"from": 0}


def new_weight_state(weight: float = 1.0) -> dict:
    return {"weight": float(weight), "hot_ticks": 0, "cool_ticks": 0,
            "last_t": 0.0}


def weight_law(
    *,
    state: dict,
    burn: float,
    bounds: WeightBounds,
    now: float,
) -> Optional[Tuple[float, str, dict]]:
    """Adaptive-WFQ law for ONE tenant. Nudges the tenant's weight toward
    SLO attainment with a bounded multiplicative step and a burn-rate
    deadband; boosted weights decay back toward 1.0 once the tenant is
    healthy again. The floor/ceiling bounds are absolute — no decision can
    starve a tenant below `floor`."""
    w = state["weight"]
    breaching = burn >= 1.0 + bounds.deadband
    healthy = burn <= 1.0 - bounds.deadband
    state["hot_ticks"] = state["hot_ticks"] + 1 if breaching else 0
    state["cool_ticks"] = state["cool_ticks"] + 1 if healthy else 0
    if now - state["last_t"] < bounds.cooldown_s:
        return None
    if breaching and state["hot_ticks"] >= bounds.sustain_ticks:
        new = min(bounds.ceiling, max(bounds.floor, w * (1.0 + bounds.step)))
        if new != w:
            state["weight"] = new
            state["last_t"] = now
            state["hot_ticks"] = 0
            return new, "weight_up", {"burn": round(burn, 3),
                                      "from": round(w, 4)}
        return None
    if (
        healthy
        and w > 1.0
        and state["cool_ticks"] >= 2 * bounds.sustain_ticks
    ):
        new = max(1.0, max(bounds.floor, w / (1.0 + bounds.step)))
        state["weight"] = new
        state["last_t"] = now
        state["cool_ticks"] = 0
        return new, "weight_decay", {"burn": round(burn, 3),
                                     "from": round(w, 4)}
    return None


def new_pd_state() -> dict:
    return {"hot_ticks": 0, "last_t": 0.0}


def pd_law(
    *,
    state: dict,
    ttft_pressure: float,
    tpot_pressure: float,
    prefill_replicas: int,
    decode_replicas: int,
    ratio_tol: float,
    sustain_ticks: int,
    cooldown_s: float,
    now: float,
) -> Optional[Tuple[int, int, str, dict]]:
    """P:D rebalance law. Pressures are dimensionless (observed latency /
    its SLO component, so 1.0 = at budget). When one side's pressure
    exceeds the other's by `ratio_tol` for `sustain_ticks`, one replica
    shifts toward the pressured phase — total replica count is conserved,
    and neither pool drops below one."""
    if ttft_pressure <= 0 and tpot_pressure <= 0:
        state["hot_ticks"] = 0
        return None
    eps = 1e-9
    ratio = (ttft_pressure + eps) / (tpot_pressure + eps)
    toward_prefill = ratio >= ratio_tol and decode_replicas > 1
    toward_decode = ratio <= 1.0 / ratio_tol and prefill_replicas > 1
    if not (toward_prefill or toward_decode):
        state["hot_ticks"] = 0
        return None
    state["hot_ticks"] += 1
    if state["hot_ticks"] < sustain_ticks or now - state["last_t"] < cooldown_s:
        return None
    state["hot_ticks"] = 0
    state["last_t"] = now
    detail = {"ttft_pressure": round(ttft_pressure, 3),
              "tpot_pressure": round(tpot_pressure, 3),
              "ratio": round(ratio, 3)}
    if toward_prefill:
        return (prefill_replicas + 1, decode_replicas - 1,
                "pd_shift_prefill", detail)
    return (prefill_replicas - 1, decode_replicas + 1,
            "pd_shift_decode", detail)


@dataclass
class DeploymentObservation:
    """One deployment's aggregated signal vector for a tick, built by the
    controller from per-replica `autopilot_signals()` probes."""

    app: str
    deployment: str
    replicas: int = 0
    role: str = "engine"  # engine | prefill | decode | router | pd_router
    queued: float = 0.0
    ongoing: float = 0.0
    burn: float = 0.0
    tenant_burn: Dict[str, float] = field(default_factory=dict)
    ttft_pressure: float = 0.0
    tpot_pressure: float = 0.0
    bounds: Optional[ReplicaBounds] = None


def aggregate_signals(app: str, deployment: str,
                      signals: List[dict]) -> DeploymentObservation:
    """Fold per-replica signal dicts into one DeploymentObservation.
    Queue depths sum (total backlog); burn rates take the max across
    replicas (worst replica exhausts the budget first); per-tenant burn
    takes the per-tenant max for the same reason."""
    obs = DeploymentObservation(app=app, deployment=deployment,
                                replicas=len(signals))
    for sig in signals:
        if not isinstance(sig, dict):
            continue
        obs.role = str(sig.get("role", obs.role))
        obs.queued += float(sig.get("queued", 0) or 0)
        obs.ongoing += float(sig.get("running", 0) or 0)
        obs.burn = max(obs.burn, float(sig.get("burn_rate", 0.0) or 0.0))
        for tenant, burn in (sig.get("tenant_burn") or {}).items():
            obs.tenant_burn[tenant] = max(
                obs.tenant_burn.get(tenant, 0.0), float(burn))
        obs.ttft_pressure = max(
            obs.ttft_pressure, float(sig.get("ttft_pressure", 0.0) or 0.0))
        obs.tpot_pressure = max(
            obs.tpot_pressure, float(sig.get("tpot_pressure", 0.0) or 0.0))
    return obs
