"""Model multiplexing: many models per replica with per-replica LRU caching.

Design parity: reference `python/ray/serve/multiplex.py` (`@serve.multiplexed` wrapping
an async model loader with an LRU of `max_num_models_per_replica`) and
`serve.get_multiplexed_model_id()` reading the current request's target model. The
router prefers replicas that already hold the requested model (cache affinity), falling
back to power-of-two-choices.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
from collections import OrderedDict
from typing import Any, Callable, Optional

_model_id_ctx: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id the current request was routed with."""
    return _model_id_ctx.get()


def _set_model_id(model_id: str):
    return _model_id_ctx.set(model_id)


def _reset_model_id(token):
    _model_id_ctx.reset(token)


_LOADING = object()  # slot reserved, model load in progress


class _ModelCache:
    """Per-replica LRU of loaded models, keyed by model id.

    The capacity bound is enforced under one cache-wide lock (reserve a slot,
    evicting as needed, BEFORE loading) so concurrent loads of distinct ids can
    never overshoot max_num_models_per_replica — the bound is the whole point of
    multiplexing device-resident models.
    """

    def __init__(self, loader: Callable, owner, max_models: int,
                 on_evict: Optional[Callable] = None):
        self._loader = loader
        self._owner = owner  # the deployment instance (None for bare functions)
        self._max = max_models
        self._on_evict = on_evict  # decorator-level callback(model_id, model)
        self._models: OrderedDict[str, Any] = OrderedDict()
        self._locks: dict[str, asyncio.Lock] = {}
        self._cap_lock = asyncio.Lock()

    @property
    def model_ids(self) -> list:
        return [k for k, v in self._models.items() if v is not _LOADING]

    async def _evict_to_fit(self):
        while len(self._models) >= self._max:
            victim_id = next(
                (k for k, v in self._models.items() if v is not _LOADING), None
            )
            if victim_id is None:
                return  # everything is mid-load; momentary overshoot is unavoidable
            evicted = self._models.pop(victim_id)
            # Device-resident models must free their HBM on evict: the
            # dedicated `__model_unload__` hook wins, then the generic
            # teardown verbs; never call __del__ directly (GC would invoke
            # it a second time — a double-release for models whose finalizer
            # frees device memory or shuts down an engine). The decorator's
            # on_evict callback fires as well (metrics, external registries)
            # and is not a substitute for the model's own unload.
            for hook in ("__model_unload__", "close", "shutdown", "cleanup"):
                fn = getattr(evicted, hook, None)
                if callable(fn):
                    try:
                        out = fn()
                        if inspect.isawaitable(out):
                            await out
                    except Exception:
                        pass  # a failing user unload hook must not wedge eviction
                    break
            if self._on_evict is not None:
                try:
                    out = self._on_evict(victim_id, evicted)
                    if inspect.isawaitable(out):
                        await out
                except Exception:
                    pass  # a failing eviction callback must not wedge eviction

    async def get(self, model_id: str):
        cached = self._models.get(model_id)
        if cached is not None and cached is not _LOADING:
            self._models.move_to_end(model_id)
            return cached
        lock = self._locks.setdefault(model_id, asyncio.Lock())
        async with lock:
            cached = self._models.get(model_id)
            if cached is not None and cached is not _LOADING:  # loaded while we waited
                self._models.move_to_end(model_id)
                return cached
            async with self._cap_lock:
                await self._evict_to_fit()
                self._models[model_id] = _LOADING
            try:
                args = (model_id,) if self._owner is None else (self._owner, model_id)
                out = self._loader(*args)
                if inspect.isawaitable(out):
                    out = await out
            except Exception:
                self._models.pop(model_id, None)
                raise
            self._models[model_id] = out
            self._locks.pop(model_id, None)
            return out


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3,
                on_evict: Optional[Callable] = None):
    """Decorate a model-loader method: `async def load(self, model_id) -> model`.

    Calls are LRU-cached per replica; the replica advertises its loaded ids so the
    router can route with cache affinity. Evicted models get their
    `__model_unload__` (or close/shutdown/cleanup) hook called — device-resident
    models must free HBM there — and the optional `on_evict(model_id, model)`
    callback fires after it (sync or async).
    """

    def wrap(loader):
        cache_attr = f"__serve_mux_cache_{loader.__name__}"

        async def wrapper(self_or_id, model_id=None):
            if model_id is None:
                # Bare function loader: called as wrapper(model_id).
                owner, mid = None, self_or_id
                holder = wrapper
            else:
                owner, mid = self_or_id, model_id
                holder = owner
            cache = getattr(holder, cache_attr, None)
            if cache is None:
                cache = _ModelCache(loader, owner, max_num_models_per_replica,
                                    on_evict=on_evict)
                try:
                    setattr(holder, cache_attr, cache)
                    caches = getattr(holder, "__serve_mux_caches__", None)
                    if caches is None:
                        caches = []
                        setattr(holder, "__serve_mux_caches__", caches)
                    caches.append(cache)
                except AttributeError:
                    pass
            return await cache.get(mid)

        wrapper.__name__ = loader.__name__
        wrapper.__serve_multiplexed__ = True
        return wrapper

    if func is not None:
        return wrap(func)
    return wrap


def loaded_model_ids(instance) -> list:
    """All model ids currently cached on a deployment instance."""
    out = []
    for cache in getattr(instance, "__serve_mux_caches__", ()):
        out.extend(cache.model_ids)
    return out
