"""gRPC ingress for Serve (reference: python/ray/serve/_private/proxy.py gRPC
proxy next to the HTTP one).

A generic unary-unary service runs inside the proxy actor alongside HTTP: any
method path `/<app>/<method>` routes to app `<app>`'s ingress deployment with a
`Request` whose body is the raw request bytes, `path` is the gRPC method, and
`headers` carries the invocation metadata. bytes replies pass through verbatim;
anything else is JSON-encoded — so clients don't need this framework's protos
(the reference similarly serves user-defined protos through a generic router).
"""

from __future__ import annotations

import json
from typing import Optional

from ray_tpu.serve._common import Request


class GrpcIngress:
    """grpc.aio server bound inside the proxy actor's event loop."""

    def __init__(self, proxy, host: str = "127.0.0.1", port: int = 9000):
        self._proxy = proxy  # HTTPProxy: reuses its routing table + handles
        self._host = host
        self._port = port
        self._server = None

    async def start(self) -> int:
        import grpc

        proxy = self._proxy

        class _Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                method = call_details.method  # "/<app>/<rpc>"

                async def unary(request_bytes: bytes, context):
                    return await _dispatch_grpc(proxy, method, request_bytes,
                                                dict(context.invocation_metadata()))

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,  # raw bytes in
                    response_serializer=None,  # raw bytes out
                )

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((_Handler(),))
        try:
            bound = self._server.add_insecure_port(f"{self._host}:{self._port}")
        except Exception:
            bound = 0
        if not bound:
            # Same-host port collision (single-host test clusters): ephemeral.
            bound = self._server.add_insecure_port(f"{self._host}:0")
        self._port = bound
        await self._server.start()
        return bound

    async def stop(self):
        if self._server is not None:
            await self._server.stop(grace=0.5)


async def _dispatch_grpc(proxy, method: str, body: bytes, metadata: dict):
    import asyncio

    parts = [p for p in method.split("/") if p]
    app = parts[0] if parts else None
    if app not in proxy._handles:
        # Fall back to route matching like HTTP ("/" prefix apps).
        app = proxy._match_app("/" + "/".join(parts))
    if app is None or app not in proxy._handles:
        raise KeyError(f"no Serve application for gRPC method {method!r}")
    request = Request(
        method="GRPC", path=method, query_params={}, headers=metadata, body=body,
    )
    handle = proxy._handles[app]
    loop = asyncio.get_running_loop()
    result = await loop.run_in_executor(
        None, lambda: handle.remote(request).result(timeout_s=60)
    )
    if isinstance(result, bytes):
        return result
    if isinstance(result, str):
        return result.encode()
    return json.dumps(result, default=str).encode()
