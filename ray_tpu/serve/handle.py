"""DeploymentHandle: the client-side router for calling deployments.

Design parity: reference `python/ray/serve/handle.py` (DeploymentHandle.remote :692 →
DeploymentResponse) and `_private/router.py` (:470 AsyncioRouter) with the default
power-of-two-choices replica scheduler (`_private/request_router/pow_2_router.py`):
pick two random replicas, send to the one with fewer locally-tracked in-flight
requests. Handles are picklable (app+deployment names) so deployments can call each
other — model composition.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._common import CONTROLLER_NAME, SERVE_NAMESPACE


class DeploymentResponse:
    """A future for one deployment request. Parity: serve.handle.DeploymentResponse.

    Replica death surfaces at result-resolution time (actor errors are delivered as
    task results in this runtime, never at submit), so failover lives here: on
    ActorDiedError the request is resubmitted through the router to a live replica.
    """

    _MAX_RETRIES = 3

    def __init__(self, ref: "ray_tpu.ObjectRef", resubmit=None):
        self._ref = ref
        self._resubmit = resubmit
        self._retries = 0

    def result(self, timeout_s: Optional[float] = None) -> Any:
        while True:
            try:
                return ray_tpu.get(self._ref, timeout=timeout_s)
            except ray_tpu.exceptions.ActorDiedError:
                if self._resubmit is None or self._retries >= self._MAX_RETRIES:
                    raise
                self._retries += 1
                self._ref = self._resubmit()

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, lambda: self.result())
        return fut.__await__()

    @property
    def object_ref(self) -> "ray_tpu.ObjectRef":
        return self._ref


class _Router:
    """Replica set cache + power-of-two-choices pick. One per handle per process."""

    _CACHE_TTL_S = 2.0

    def __init__(self, app: str, deployment: str):
        self._app = app
        self._deployment = deployment
        self._replicas: List = []
        self._version = -1
        self._fetched_at = 0.0
        self._inflight: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def _controller(self):
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and self._replicas and now - self._fetched_at < self._CACHE_TTL_S:
            return
        info = ray_tpu.get(
            self._controller().get_replicas.remote(self._app, self._deployment)
        )
        with self._lock:
            self._version = info["version"]
            self._replicas = info["replicas"]
            self._fetched_at = now
            self._inflight = {
                a._actor_id: self._inflight.get(a._actor_id, 0) for a in self._replicas
            }

    def pick(self):
        self._refresh()
        deadline = time.monotonic() + 30
        while not self._replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment {self._app}#{self._deployment}"
                )
            time.sleep(0.05)
            self._refresh(force=True)
        with self._lock:
            if len(self._replicas) == 1:
                return self._replicas[0]
            a, b = random.sample(self._replicas, 2)
            pick = a if self._inflight.get(a._actor_id, 0) <= self._inflight.get(
                b._actor_id, 0
            ) else b
            self._inflight[pick._actor_id] = self._inflight.get(pick._actor_id, 0) + 1
            return pick

    def done(self, replica):
        with self._lock:
            if replica._actor_id in self._inflight:
                self._inflight[replica._actor_id] = max(
                    0, self._inflight[replica._actor_id] - 1
                )

    def evict(self):
        with self._lock:
            self._replicas = []
            self._fetched_at = 0.0


# Routers are shared per (app, deployment) within a process so every handle —
# including the throwaway children __getattr__ builds for handle.method.remote() —
# reuses one replica cache and one in-flight load map.
_ROUTERS: Dict[tuple, _Router] = {}
_ROUTERS_LOCK = threading.Lock()


def _shared_router(app: str, deployment: str) -> _Router:
    key = (app, deployment)
    with _ROUTERS_LOCK:
        router = _ROUTERS.get(key)
        if router is None:
            router = _ROUTERS[key] = _Router(app, deployment)
        return router


class DeploymentHandle:
    def __init__(self, app: str, deployment: str, method_name: str = "__call__"):
        self._app = app
        self._deployment = deployment
        self._method_name = method_name
        self._router: Optional[_Router] = None

    def __reduce__(self):
        return (DeploymentHandle, (self._app, self._deployment, self._method_name))

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._app, self._deployment, name)

    def options(self, *, method_name: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._app, self._deployment, method_name or self._method_name
        )

    def _get_router(self) -> _Router:
        if self._router is None:
            self._router = _shared_router(self._app, self._deployment)
        return self._router

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        # Deployment responses compose: pass the underlying refs so the runtime
        # resolves them as task dependencies (no blocking round-trip here).
        args = tuple(
            a.object_ref if isinstance(a, DeploymentResponse) else a for a in args
        )
        kwargs = {
            k: (v.object_ref if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        router = self._get_router()
        method = self._method_name

        def submit():
            replica = router.pick()
            ref = replica.handle_request.remote(method, args, kwargs)
            # In-flight bookkeeping: decremented when the result resolves.
            ray_tpu.global_worker().memory_store.add_done_callback(
                ref.id, lambda *_a, _r=replica: router.done(_r)
            ) or router.done(replica)
            return ref

        def resubmit():
            router.evict()  # stale table: the picked replica was dead
            return submit()

        return DeploymentResponse(submit(), resubmit)

    def __repr__(self):
        return f"DeploymentHandle({self._app}#{self._deployment}.{self._method_name})"
