"""DeploymentHandle: the client-side router for calling deployments.

Design parity: reference `python/ray/serve/handle.py` (DeploymentHandle.remote :692 →
DeploymentResponse) and `_private/router.py` (:470 AsyncioRouter) with the default
power-of-two-choices replica scheduler (`_private/request_router/pow_2_router.py`):
pick two random replicas, send to the one with fewer locally-tracked in-flight
requests. Handles are picklable (app+deployment names) so deployments can call each
other — model composition.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._common import (
    CONTROLLER_NAME,
    SERVE_NAMESPACE,
    ControllerUnavailableError,
    DeploymentNotFoundError,
)


class DeploymentResponse:
    """A future for one deployment request. Parity: serve.handle.DeploymentResponse.

    Replica death surfaces at result-resolution time (actor errors are delivered as
    task results in this runtime, never at submit), so failover lives here: on
    ActorDiedError the request is resubmitted through the router to a live replica.
    """

    @property
    def _MAX_RETRIES(self):
        from ray_tpu._private.config import CONFIG

        return CONFIG.serve_handle_max_retries

    def __init__(self, ref: "ray_tpu.ObjectRef", resubmit=None):
        self._ref = ref
        self._resubmit = resubmit
        self._retries = 0

    def result(self, timeout_s: Optional[float] = None) -> Any:
        while True:
            try:
                return ray_tpu.get(self._ref, timeout=timeout_s)
            except ray_tpu.exceptions.ActorDiedError:
                if self._resubmit is None or self._retries >= self._MAX_RETRIES:
                    raise
                self._retries += 1
                self._ref = self._resubmit()

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, lambda: self.result())
        return fut.__await__()

    @property
    def object_ref(self) -> "ray_tpu.ObjectRef":
        return self._ref


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment response: yields the VALUES the
    endpoint streams, as they are produced (reference:
    serve.handle.DeploymentResponseGenerator over a streaming replica call)."""

    def __init__(self, ref_gen, on_done=None, cancel=None):
        self._ref_gen = ref_gen  # ObjectRefGenerator
        self._on_done = on_done
        self._cancel = cancel
        self._finished = False
        self._exhausted = False  # producer ran to completion (no cancel needed)

    def _finish(self):
        if not self._finished:
            self._finished = True
            if self._on_done is not None:
                self._on_done()

    def close(self):
        """Release router bookkeeping for an abandoned stream, and — when the
        producer is still live — fire the replica-side cancel so the endpoint
        generator's finally-blocks run (docs/generation.md cancel plane)."""
        if not self._exhausted and self._cancel is not None:
            try:
                self._cancel()
            except Exception:
                pass  # cancel is best-effort; the replica may already be gone
        self._finish()

    def __del__(self):
        try:
            self._finish()
        except Exception:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        try:
            ref = next(self._ref_gen)
            return ray_tpu.get(ref)
        except StopIteration:
            self._exhausted = True
            self._finish()
            raise
        except Exception:
            # An error ref mid-stream must also release the router's inflight
            # count, or repeated streaming errors skew the pow-2 load metric.
            self._finish()
            raise

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        done = object()

        def step():
            try:
                return self.__next__()
            except StopIteration:
                return done

        item = await asyncio.get_running_loop().run_in_executor(None, step)
        if item is done:
            raise StopAsyncIteration
        return item

    @property
    def object_ref_gen(self):
        return self._ref_gen


def affinity_pick(replicas, holder_ids, inflight):
    """Least-loaded replica among the holders of some cached resource — the
    ONE cache-affinity primitive shared by serve multiplexing (model-id
    affinity in `_Router.pick`) and the DP LLM router's adapter-residency
    path (`dp_serve.DPRouter`). `holder_ids` is the actor-id set advertising
    the resource; returns None when no holder is live (caller falls back to
    its balanced pick)."""
    holders = [r for r in replicas if r._actor_id in holder_ids]
    if not holders:
        return None
    return min(holders, key=lambda r: inflight.get(r._actor_id, 0))


class _Router:
    """Replica set cache + power-of-two-choices pick. One per handle per process."""

    @property
    def _CACHE_TTL_S(self) -> float:
        from ray_tpu._private.config import CONFIG

        return CONFIG.serve_router_cache_ttl_s

    @property
    def _RECOVERY_DEADLINE_S(self) -> float:
        # The window a routing call rides through control-plane downtime
        # (controller SIGKILL + restart, GCS restart) before surfacing a typed
        # ControllerUnavailableError. Matches the GCS client's own rpc window.
        from ray_tpu._private.config import CONFIG

        return CONFIG.gcs_rpc_timeout_s

    def __init__(self, app: str, deployment: str):
        self._app = app
        self._deployment = deployment
        self._replicas: List = []
        self._exists = True  # False only on a DEFINITIVE "app deleted" answer
        self._version = -1
        self._fetched_at = 0.0
        self._controller_handle = None
        self._inflight: Dict[Any, int] = {}
        # Multiplexing: cluster-wide replica-reported model ids (refreshed with
        # the routing table — reference routes on replica-reported ids) plus a
        # local fallback affinity for models routed between controller polls.
        self._mux: Dict[Any, list] = {}  # actor_id -> [model ids]
        self._model_affinity: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._last_wake = 0.0

    def _wake(self):
        """Scale-to-zero cold start (docs/autoscale.md): this routing call
        found an EXISTING deployment with zero replicas — tell the
        autopilot a requester is waiting so it can spawn one without
        waiting out its pressure hysteresis. Fire-and-forget and throttled;
        a no-autopilot controller just answers False."""
        now = time.monotonic()
        if now - self._last_wake < 1.0:
            return
        self._last_wake = now
        try:
            self._controller().autopilot_wake.remote(  # raylint: disable=RL501 (fire-and-forget wake; pick() retry loop observes the result)
                self._app, self._deployment)
        except Exception:
            pass  # controller unreachable: the retry loop already backs off

    def _controller(self):
        # Cached handle: the by-name lookup needs the GCS, but calls on a
        # resolved handle ride direct connections — so a router that has EVER
        # reached the controller keeps refreshing its table straight through a
        # GCS outage (and through controller restarts, which keep the actor
        # id). Cleared on call failure to force re-resolution.
        if self._controller_handle is None:
            self._controller_handle = ray_tpu.get_actor(
                CONTROLLER_NAME, namespace=SERVE_NAMESPACE
            )
        return self._controller_handle

    def _refresh(self, force: bool = False):
        """Refresh the routing table, serving STALE on control-plane downtime.

        The controller restarting (or the GCS under it) must not fail calls
        that live replicas can still serve: a refresh error with a cached
        replica set keeps the cache (stale-while-error) and retries after one
        TTL. Only a caller with NO table to fall back on sees the error."""
        now = time.monotonic()
        if not force and self._replicas and now - self._fetched_at < self._CACHE_TTL_S:
            return
        try:
            info = ray_tpu.get(
                self._controller().get_replicas.remote(self._app, self._deployment),
                timeout=5.0,
            )
        except Exception:
            self._controller_handle = None  # re-resolve by name next attempt
            if self._replicas:
                with self._lock:
                    self._fetched_at = now  # back off one TTL, keep serving stale
                return
            raise
        with self._lock:
            self._exists = bool(info.get("exists", True))
            self._version = info["version"]
            self._replicas = info["replicas"]
            self._mux = info.get("multiplexed") or {}
            self._fetched_at = now
            self._inflight = {
                a._actor_id: self._inflight.get(a._actor_id, 0) for a in self._replicas
            }

    def pick(self, model_id: str = ""):
        deadline = time.monotonic() + self._RECOVERY_DEADLINE_S
        delay = 0.05
        last_err: Optional[Exception] = None
        force = False
        while True:
            try:
                self._refresh(force=force)
                last_err = None
            except Exception as e:  # controller unreachable and no cache
                last_err = e
            if last_err is None and not self._exists:
                raise DeploymentNotFoundError(
                    f"deployment {self._app}#{self._deployment} does not exist "
                    f"(app deleted or never deployed)"
                )
            if self._replicas:
                break
            if time.monotonic() > deadline:
                if last_err is not None:
                    raise ControllerUnavailableError(
                        f"serve controller unreachable for "
                        f"{self._RECOVERY_DEADLINE_S:.0f}s while routing "
                        f"{self._app}#{self._deployment}; retry once the "
                        f"control plane recovers"
                    ) from last_err
                raise RuntimeError(
                    f"no replicas for deployment {self._app}#{self._deployment}"
                )
            if last_err is None and self._exists:
                self._wake()
            # Exponential backoff + jitter: a fleet of handles re-resolving a
            # restarted controller must not stampede it.
            time.sleep(delay * (0.5 + random.random()))
            delay = min(delay * 2.0, 1.0)
            force = True
        with self._lock:
            if model_id:
                # Cluster-wide affinity first: any replica REPORTING the model
                # loaded (controller-polled) serves it without a reload, even
                # if this caller never routed it before. Least-loaded among
                # the holders; local last-routed affinity as the fallback for
                # models loaded since the last poll.
                pick = affinity_pick(
                    self._replicas,
                    {r._actor_id for r in self._replicas
                     if model_id in self._mux.get(r._actor_id, ())},
                    self._inflight,
                )
                if pick is not None:
                    self._inflight[pick._actor_id] = (
                        self._inflight.get(pick._actor_id, 0) + 1
                    )
                    self._model_affinity[model_id] = pick._actor_id
                    return pick
                aff = self._model_affinity.get(model_id)
                if aff is not None:
                    for r in self._replicas:
                        if r._actor_id == aff:
                            self._inflight[aff] = self._inflight.get(aff, 0) + 1
                            return r
            if len(self._replicas) == 1:
                pick = self._replicas[0]
            else:
                a, b = random.sample(self._replicas, 2)
                pick = a if self._inflight.get(a._actor_id, 0) <= self._inflight.get(
                    b._actor_id, 0
                ) else b
            self._inflight[pick._actor_id] = self._inflight.get(pick._actor_id, 0) + 1
            if model_id:
                self._model_affinity[model_id] = pick._actor_id
            return pick

    def replicas(self) -> List:
        """Current replica actor handles (refreshing the cached table). For
        affinity-aware callers (e.g. the DP LLM router's prefix-cache
        routing) that pick a replica themselves via pick_replica()."""
        self._refresh()
        with self._lock:
            return list(self._replicas)

    def loads(self) -> Dict[Any, int]:
        """actor_id -> locally tracked in-flight requests (the pow-2 metric)."""
        with self._lock:
            return dict(self._inflight)

    def pick_replica(self, replica):
        """Route to a SPECIFIC replica, with the same in-flight bookkeeping
        pick() applies — the caller must pair it with done() (directly or via
        a done-callback) exactly like pick()."""
        with self._lock:
            self._inflight[replica._actor_id] = (
                self._inflight.get(replica._actor_id, 0) + 1
            )
        return replica

    def done(self, replica):
        with self._lock:
            if replica._actor_id in self._inflight:
                self._inflight[replica._actor_id] = max(
                    0, self._inflight[replica._actor_id] - 1
                )

    def evict(self):
        with self._lock:
            self._replicas = []
            self._fetched_at = 0.0


# Routers are shared per (app, deployment) within a process so every handle —
# including the throwaway children __getattr__ builds for handle.method.remote() —
# reuses one replica cache and one in-flight load map.
_ROUTERS: Dict[tuple, _Router] = {}
_ROUTERS_LOCK = threading.Lock()


def _shared_router(app: str, deployment: str) -> _Router:
    key = (app, deployment)
    with _ROUTERS_LOCK:
        router = _ROUTERS.get(key)
        if router is None:
            router = _ROUTERS[key] = _Router(app, deployment)
        return router


class DeploymentHandle:
    def __init__(self, app: str, deployment: str, method_name: str = "__call__",
                 stream: bool = False, multiplexed_model_id: str = ""):
        self._app = app
        self._deployment = deployment
        self._method_name = method_name
        self._stream = stream
        self._multiplexed_model_id = multiplexed_model_id
        self._router: Optional[_Router] = None

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self._app, self._deployment, self._method_name, self._stream,
             self._multiplexed_model_id),
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(
            self._app, self._deployment, name, self._stream, self._multiplexed_model_id
        )

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._app,
            self._deployment,
            method_name or self._method_name,
            self._stream if stream is None else stream,
            self._multiplexed_model_id
            if multiplexed_model_id is None
            else multiplexed_model_id,
        )

    def _get_router(self) -> _Router:
        if self._router is None:
            self._router = _shared_router(self._app, self._deployment)
        return self._router

    def broadcast(self, *args, **kwargs) -> list:
        """Call the bound method on EVERY current replica and return all results
        (control-plane operations like installing a LoRA adapter must reach the
        whole replica set, not one routed pick). Replicas added later — scale-up,
        recovery — do NOT receive past broadcasts; re-broadcast after scaling."""
        router = self._get_router()
        router._refresh(force=True)
        responses = [
            r.handle_request.remote(self._method_name, args, kwargs)
            for r in list(router._replicas)
        ]
        return [ray_tpu.get(ref, timeout=120) for ref in responses]

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        # Deployment responses compose: pass the underlying refs so the runtime
        # resolves them as task dependencies (no blocking round-trip here).
        args = tuple(
            a.object_ref if isinstance(a, DeploymentResponse) else a for a in args
        )
        kwargs = {
            k: (v.object_ref if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        router = self._get_router()
        method = self._method_name
        model_id = self._multiplexed_model_id
        if model_id:
            from ray_tpu.serve._replica import MUX_KWARG

            kwargs = {**kwargs, MUX_KWARG: model_id}

        if self._stream:
            import uuid

            from ray_tpu.serve._replica import STREAM_CANCEL_KWARG

            cancel_token = uuid.uuid4().hex
            kwargs = {**kwargs, STREAM_CANCEL_KWARG: cancel_token}
            replica = router.pick(model_id)
            ref_gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, args, kwargs)

            def cancel():
                replica.cancel_stream.remote(cancel_token)  # raylint: disable=RL501 (fire-and-forget cancel; the stream's own finish is the observable)

            return DeploymentResponseGenerator(
                ref_gen, on_done=lambda: router.done(replica), cancel=cancel
            )

        def submit():
            replica = router.pick(model_id)
            ref = replica.handle_request.remote(method, args, kwargs)
            # In-flight bookkeeping: decremented when the result resolves.
            ray_tpu.global_worker().memory_store.add_done_callback(
                ref.id, lambda *_a, _r=replica: router.done(_r)
            ) or router.done(replica)
            return ref

        def resubmit():
            router.evict()  # stale table: the picked replica was dead
            return submit()

        return DeploymentResponse(submit(), resubmit)

    def __repr__(self):
        return f"DeploymentHandle({self._app}#{self._deployment}.{self._method_name})"
