"""Declarative Serve config: schema, validation, apply, build.

Parity: reference `python/ray/serve/schema.py` (ServeDeploySchema →
ServeApplicationSchema → DeploymentSchema) plus the operational halves of
`python/ray/serve/scripts.py` `serve deploy` (:333), `serve status` (:696) and
`serve build` (:814). The config file is the declarative source of truth:
`apply_config` has PUT semantics — applications present in the live cluster
but absent from the config are deleted, present ones are reconciled to the
config's replica/autoscaling targets (idempotent re-apply), and new ones are
imported and deployed.

A config file looks like:

```yaml
applications:
- name: default
  route_prefix: /
  import_path: my_module:app        # an Application or a builder callable
  args: {model: gpt2}               # passed to a builder callable
  deployments:                      # per-deployment overrides by name
  - name: Model
    num_replicas: 2
    max_ongoing_requests: 32
  - name: Tokenizer
    autoscaling_config: {min_replicas: 1, max_replicas: 4}
```
"""

from __future__ import annotations

import dataclasses
import importlib
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class ServeConfigError(ValueError):
    """Invalid declarative serve config."""


@dataclass
class DeploymentSchema:
    """Per-deployment overrides (reference schema.py DeploymentSchema)."""

    name: str
    num_replicas: Optional[Any] = None  # int | "auto"
    max_ongoing_requests: Optional[int] = None
    autoscaling_config: Optional[dict] = None
    user_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None

    @classmethod
    def from_dict(cls, d: dict) -> "DeploymentSchema":
        if not isinstance(d, dict) or "name" not in d:
            raise ServeConfigError(f"deployment entry needs a name: {d!r}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ServeConfigError(
                f"unknown deployment option(s) {sorted(unknown)} for "
                f"{d['name']!r}; known: {sorted(known - {'name'})}"
            )
        return cls(**d)


@dataclass
class ServeApplicationSchema:
    """One application (reference schema.py ServeApplicationSchema)."""

    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = "/"
    args: Dict[str, Any] = field(default_factory=dict)
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "ServeApplicationSchema":
        if not isinstance(d, dict) or "import_path" not in d:
            raise ServeConfigError(
                f"application entry needs an import_path: {d!r}"
            )
        if ":" not in d["import_path"]:
            raise ServeConfigError(
                f"import_path must be 'module:attribute', got "
                f"{d['import_path']!r}"
            )
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ServeConfigError(
                f"unknown application option(s) {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        deps = [DeploymentSchema.from_dict(x) for x in d.get("deployments", [])]
        return cls(
            import_path=d["import_path"],
            name=d.get("name", "default"),
            route_prefix=d.get("route_prefix", "/"),
            args=d.get("args") or {},
            deployments=deps,
        )


@dataclass
class ServeDeploySchema:
    """The whole declarative state (reference schema.py ServeDeploySchema)."""

    applications: List[ServeApplicationSchema]
    http_options: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "ServeDeploySchema":
        if not isinstance(d, dict) or "applications" not in d:
            raise ServeConfigError("config needs a top-level 'applications' list")
        apps = [ServeApplicationSchema.from_dict(a) for a in d["applications"]]
        names = [a.name for a in apps]
        if len(set(names)) != len(names):
            raise ServeConfigError(f"duplicate application names in {names}")
        prefixes = [a.route_prefix for a in apps if a.route_prefix is not None]
        if len(set(prefixes)) != len(prefixes):
            raise ServeConfigError(f"duplicate route_prefix in {prefixes}")
        return cls(applications=apps, http_options=d.get("http_options") or {})


def _import_target(import_path: str, args: dict):
    """Resolve module:attr to an Application (calling a builder if needed)."""
    mod_name, _, attr = import_path.partition(":")
    if "" not in sys.path and "." not in sys.path:
        sys.path.insert(0, ".")  # match the reference CLI's cwd import rule
    mod = importlib.import_module(mod_name)
    try:
        target = getattr(mod, attr)
    except AttributeError:
        raise ServeConfigError(
            f"{mod_name!r} has no attribute {attr!r}"
        ) from None
    from ray_tpu.serve import Application

    if isinstance(target, Application):
        if args:
            raise ServeConfigError(
                f"{import_path} is a bound Application; 'args' requires a "
                "builder function"
            )
        return target
    if callable(target):
        app = target(args) if args else target()
        if not isinstance(app, Application):
            raise ServeConfigError(
                f"builder {import_path} returned {type(app).__name__}, "
                "expected an Application (did you forget .bind()?)"
            )
        return app
    raise ServeConfigError(
        f"{import_path} is neither an Application nor a builder callable"
    )


def _apply_overrides(acc: Dict[str, dict], overrides: List[DeploymentSchema],
                     app_name: str) -> Dict[str, dict]:
    """Return a copy of the collected specs with the schema's per-deployment
    overrides applied; unknown deployment names are config errors (catching
    typos is the point of a declarative file).

    The input specs alias the imported module's `Deployment.config` dataclass
    instances, so overridden configs are deep-copied first: a long-lived
    driver re-applying configs (or later calling plain serve.run on the same
    app) must never see one apply's overrides leak into the module's state."""
    import copy

    from ray_tpu.serve import AutoscalingConfig

    out = {name: dict(spec) for name, spec in acc.items()}
    for ov in overrides:
        spec = out.get(ov.name)
        if spec is None:
            raise ServeConfigError(
                f"app {app_name!r} has no deployment {ov.name!r}; "
                f"bound deployments: {sorted(out)}"
            )
        cfg = copy.deepcopy(spec["config"])
        spec["config"] = cfg
        if ov.num_replicas is not None:
            if ov.num_replicas == "auto":
                cfg.autoscaling_config = (
                    cfg.autoscaling_config or AutoscalingConfig()
                )
            elif isinstance(ov.num_replicas, int) and ov.num_replicas >= 1:
                cfg.num_replicas = ov.num_replicas
            else:
                raise ServeConfigError(
                    f"num_replicas must be a positive int or 'auto', got "
                    f"{ov.num_replicas!r} for {ov.name!r}"
                )
        if ov.max_ongoing_requests is not None:
            cfg.max_ongoing_requests = int(ov.max_ongoing_requests)
        if ov.autoscaling_config is not None:
            cfg.autoscaling_config = AutoscalingConfig(**ov.autoscaling_config)
        if ov.user_config is not None:
            cfg.user_config = ov.user_config
        if ov.ray_actor_options is not None:
            cfg.ray_actor_options = ov.ray_actor_options
    return out


def apply_config(config: dict, *, wait_ready: bool = False,
                 timeout_s: float = 120.0) -> Dict[str, str]:
    """Deploy a declarative config (PUT semantics). Returns {app: outcome}.

    Outcomes: "deployed" (new or changed), "deleted" (live but absent from
    the config). Re-applying an unchanged config is a no-op reconcile: the
    controller sees the same specs and keeps its replicas.
    """
    import inspect as _inspect
    import time as _time

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import _collect_deployments

    schema = ServeDeploySchema.from_dict(config)
    controller = serve.start(schema.http_options or None)
    outcomes: Dict[str, str] = {}

    live = set(ray_tpu.get(controller.list_apps.remote()))
    wanted = {a.name for a in schema.applications}
    for gone in sorted(live - wanted):
        ray_tpu.get(controller.delete_app.remote(gone))
        outcomes[gone] = "deleted"

    for app_schema in schema.applications:
        app = _import_target(app_schema.import_path, app_schema.args)
        acc: Dict[str, dict] = {}
        _collect_deployments(app, app_schema.name, acc)
        acc = _apply_overrides(acc, app_schema.deployments, app_schema.name)
        ingress_name = app.deployment.name
        target = app.deployment.target
        call = (target if not _inspect.isclass(target)
                else getattr(target, "__call__", None))
        ingress_streaming = bool(
            call is not None
            and (_inspect.isgeneratorfunction(call)
                 or _inspect.isasyncgenfunction(call))
        )
        ray_tpu.get(controller.deploy_app.remote(
            app_schema.name, acc, app_schema.route_prefix, ingress_name,
            ingress_streaming,
        ))
        outcomes[app_schema.name] = "deployed"

    if wait_ready:
        deadline = _time.monotonic() + timeout_s
        pending = [a.name for a in schema.applications]
        while pending and _time.monotonic() < deadline:
            pending = [
                n for n in pending
                if not ray_tpu.get(controller.ready.remote(n))
            ]
            if pending:
                _time.sleep(0.2)
        if pending:
            raise TimeoutError(f"applications not ready: {pending}")
    return outcomes


def status_report() -> dict:
    """Declarative-shaped status: per app, per deployment, replica counts and
    a coarse state (reference `serve status` output shape)."""
    from ray_tpu import serve

    apps = serve.status()
    report: Dict[str, Any] = {"applications": {}}
    for name, info in apps.items():
        deps = {}
        all_ready = True
        for dname, d in info.get("deployments", {}).items():
            target = d.get("target")
            running = d.get("num_replicas", 0)
            # Autoscaled deployments have target=None: running count is truth.
            ready = target is None or running >= target
            all_ready = all_ready and ready
            deps[dname] = {
                "status": "HEALTHY" if ready else "UPDATING",
                "replica_states": {"RUNNING": running},
                "target_num_replicas": target,
            }
        report["applications"][name] = {
            "status": "RUNNING" if all_ready else "DEPLOYING",
            "route_prefix": info.get("route_prefix"),
            "deployments": deps,
        }
    return report


def build_config(import_paths: List[str]) -> dict:
    """Scaffold a config dict from bound applications (reference `serve
    build`): imports each target and emits its deployment names with their
    CURRENT config values, ready to edit and `serve deploy`."""
    from ray_tpu.serve import _collect_deployments

    apps_out = []
    for i, path in enumerate(import_paths):
        app = _import_target(path, {})
        acc: Dict[str, dict] = {}
        name = "default" if len(import_paths) == 1 else f"app{i + 1}"
        _collect_deployments(app, name, acc)
        deployments = []
        for dname, spec in acc.items():
            cfg = spec["config"]
            entry: Dict[str, Any] = {"name": dname}
            if cfg.num_replicas != 1:
                entry["num_replicas"] = cfg.num_replicas
            entry["max_ongoing_requests"] = cfg.max_ongoing_requests
            if cfg.autoscaling_config is not None:
                entry["autoscaling_config"] = dataclasses.asdict(
                    cfg.autoscaling_config
                )
            if cfg.user_config:
                entry["user_config"] = cfg.user_config
            if cfg.ray_actor_options:
                entry["ray_actor_options"] = cfg.ray_actor_options
            deployments.append(entry)
        apps_out.append({
            "name": name,
            "route_prefix": "/" if i == 0 else f"/app{i + 1}",
            "import_path": path,
            "deployments": deployments,
        })
    return {"applications": apps_out}
