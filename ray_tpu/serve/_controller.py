"""ServeController: the serve control plane actor.

Design parity: reference `python/ray/serve/_private/controller.py` (:103) +
`application_state.py` + `deployment_state.py` — hold the desired state (apps →
deployments → configs), reconcile replica actors toward it (create missing, kill
excess, replace dead), serve routing tables to handles, and run the autoscaling
policy over replica stats (`autoscaling_policy.py`).
"""

from __future__ import annotations

import asyncio
import math
import time
import traceback
from typing import Any, Dict, List, Optional

from ray_tpu.serve._common import (
    AUTOPILOT_KEY,
    CONTROLLER_KV_NS,
    REGISTRY_KEY,
    TARGET_STATE_KEY,
)


class ServeController:
    """Async actor. One per cluster, named SERVE_CONTROLLER in the serve namespace.

    Durable control plane (docs/fault_tolerance.md): declarative target state
    (app configs, deployment specs, autoscale targets, http options) and the
    replica/proxy registry persist to GCS KV on every mutation. The actor runs
    with max_restarts=-1; a restarted incarnation lazily recovers the persisted
    state on its first method call, probes the registered actors, and RE-ADOPTS
    the ones still alive — live replicas keep serving through a controller death
    or a GCS restart, and reconciliation only replaces what actually died.
    """

    def __init__(self):
        # app -> deployment -> spec dict (blobs + DeploymentConfig)
        self._apps: Dict[str, Dict[str, dict]] = {}
        # app -> deployment -> list of replica ActorHandles
        self._replicas: Dict[str, Dict[str, list]] = {}
        self._versions: Dict[str, int] = {}
        self._loop_started = False
        self._shutting_down = False
        # Durable-state bookkeeping: recovery runs at most once per
        # incarnation (lazily, on the first method call — __init__ runs off
        # the actor's event loop and must not block on KV I/O).
        self._recovered = False
        self._recover_lock = asyncio.Lock()
        self._state_dirty = False
        self._registry_snapshot: Optional[tuple] = None
        # autoscale bookkeeping: (app, dep) -> last scale decision time
        self._last_scale: Dict[tuple, float] = {}
        # health bookkeeping OUTSIDE the spec dicts: redeploys must not reset a
        # live replica's "has been healthy" status or its startup clock.
        # (app, dep) -> {"healthy": set[actor_id], "created": {actor_id: t}}
        self._health: Dict[tuple, dict] = {}
        # Per-node HTTP proxies (reference: one ProxyActor per node, proxy.py):
        # node_id hex -> (actor handle, port). Reconciled against cluster
        # membership in the control loop once ensure_proxies() arms it.
        self._http_options: Optional[dict] = None
        self._proxies: Dict[str, tuple] = {}
        # Serializes proxy reconciliation: concurrent ensure_proxies calls
        # (driver + control loop) must not both create/start the same node's
        # proxy — interleaved starts split the bound-port table.
        self._proxy_lock = asyncio.Lock()
        self._mux_ids: Dict[str, dict] = {}  # "app#dep" -> {actor_id: [model ids]}
        # SLO autopilot (docs/autoscale.md): lazily constructed on the first
        # tick with CONFIG.serve_autopilot on, or recovered from its own KV
        # record. Its targets/cooldowns persist separately from the
        # declarative state so deploy replays cannot clobber them.
        self._autopilot = None
        self._autopilot_last = 0.0
        self._autopilot_wake_ts: Dict[str, float] = {}

    # -- durable control-plane state --------------------------------------
    #
    # Two KV records in CONTROLLER_KV_NS:
    #   TARGET_STATE_KEY — declarative intent (apps/specs/configs/http options):
    #     what the operator asked for; enough to rebuild everything from cold.
    #   REGISTRY_KEY — the replica/proxy actor handles the previous incarnation
    #     created: what exists RIGHT NOW, so recovery adopts live actors
    #     instead of replacing them (replica processes hold warm compiled
    #     models; a cold-start would drop every in-flight request).

    @staticmethod
    def _kv_io(fn):
        """Run a blocking GCS KV op off the actor's event loop."""
        loop = asyncio.get_running_loop()
        return loop.run_in_executor(None, fn)

    async def _ensure_recovered(self):
        if self._recovered:
            return
        async with self._recover_lock:
            if self._recovered:
                return
            await self._recover()  # raylint: disable=RL905 (the recover lock exists precisely to hold callers across this await: nothing may proceed on unrecovered state)
            self._recovered = True
        self._arm_control_loop()

    def _arm_control_loop(self):
        if not self._loop_started:
            # Restarted incarnations get no run_control_loop call from a
            # driver; the loop re-arms off whichever method call (proxy route
            # refresh, handle routing, a redeploy) touched the controller.
            asyncio.get_running_loop().create_task(self.run_control_loop())

    async def _recover(self):
        import cloudpickle

        import ray_tpu
        from ray_tpu.serve._common import async_get

        w = ray_tpu.global_worker()
        state_blob = await self._kv_io(
            lambda: w.gcs_kv_get(CONTROLLER_KV_NS, TARGET_STATE_KEY)
        )
        if state_blob is None:
            return  # fresh control plane: nothing persisted
        state = cloudpickle.loads(state_blob)
        self._apps = state.get("apps") or {}
        self._http_options = state.get("http_options")
        registry_blob = await self._kv_io(
            lambda: w.gcs_kv_get(CONTROLLER_KV_NS, REGISTRY_KEY)
        )
        registry = cloudpickle.loads(registry_blob) if registry_blob else {}
        # Autopilot law state (targets, cooldown wall-clocks, tenant
        # weights): a restarted controller resumes mid-loop — remaining
        # cooldowns are honored, so recovery cannot double-fire a scale
        # decision the previous incarnation just took.
        ap_blob = await self._kv_io(
            lambda: w.gcs_kv_get(CONTROLLER_KV_NS, AUTOPILOT_KEY)
        )
        if ap_blob:
            try:
                from ray_tpu._private.config import CONFIG
                from ray_tpu.serve.autopilot import Autopilot

                self._autopilot = Autopilot.load(
                    cloudpickle.loads(ap_blob),
                    decision_log_cap=CONFIG.serve_autopilot_decision_log)
            except Exception:
                traceback.print_exc()  # corrupt blob: start the loop cold
        self._versions = dict(registry.get("versions") or {})

        # Probe every registered actor CONCURRENTLY; adopt the live ones.
        async def probe(handle):
            try:
                await async_get(handle.ready.remote(), timeout=15)
                return True
            except Exception:
                return False

        candidates: List[tuple] = []  # (kind, app, dep_or_nid, handle, extra)
        for app, deps in (registry.get("replicas") or {}).items():
            for dep, handles in deps.items():
                for h in handles:
                    candidates.append(("replica", app, dep, h, None))
        for nid, (h, port) in (registry.get("proxies") or {}).items():
            candidates.append(("proxy", None, nid, h, port))
        alive = await asyncio.gather(*(probe(c[3]) for c in candidates))
        adopted = 0
        for (kind, app, key, handle, extra), ok in zip(candidates, alive):
            if not ok:
                continue
            adopted += 1
            if kind == "replica":
                self._replicas.setdefault(app, {}).setdefault(key, []).append(handle)
                health = self._health.setdefault((app, key), {
                    "healthy": set(), "created": {},
                })
                # Adopted replicas answered the probe: they are healthy NOW,
                # so a later silence means death, not a startup grace period.
                health["healthy"].add(handle._actor_id)
                health["created"][handle._actor_id] = time.monotonic()
            else:
                self._proxies[key] = (handle, extra)
        # Registry shrank to the adopted survivors: persist the pruned view and
        # bump versions where the set changed so routers refetch.
        for app, deps in (registry.get("replicas") or {}).items():
            for dep, handles in deps.items():
                if len(self._replicas.get(app, {}).get(dep, [])) != len(handles):
                    self._bump(app, dep)
        await self._persist_registry(force=True)
        try:
            from ray_tpu.util.metrics import Counter

            Counter(
                "controller_recoveries_total",
                "control-plane recoveries from persisted state",
                tag_keys=("plane",),
            ).inc(1.0, tags={"plane": "serve"})
        except Exception:
            pass  # observability only: a metrics hiccup must not fail recovery

    def _persistable_apps(self) -> dict:
        """Deep-ish copy of the app table without transient reconcile keys."""
        out: Dict[str, Dict[str, Any]] = {}
        for app, deps in self._apps.items():
            out[app] = {}
            for name, spec in deps.items():
                if name == "__meta__":
                    out[app][name] = dict(spec)
                else:
                    out[app][name] = {
                        k: v for k, v in spec.items() if k != "_dead"
                    }
        return out

    async def _persist_state(self):
        import cloudpickle

        import ray_tpu

        blob = cloudpickle.dumps(
            {"apps": self._persistable_apps(), "http_options": self._http_options}
        )
        w = ray_tpu.global_worker()
        await self._kv_io(
            lambda: w.gcs_kv_put(CONTROLLER_KV_NS, TARGET_STATE_KEY, blob)
        )
        self._state_dirty = False

    def _registry_fingerprint(self) -> tuple:
        return (
            tuple(
                (app, dep, tuple(sorted(r._actor_id.hex() for r in handles)))
                for app, deps in sorted(self._replicas.items())
                for dep, handles in sorted(deps.items())
            ),
            tuple(
                (nid, h._actor_id.hex(), port)
                for nid, (h, port) in sorted(self._proxies.items())
            ),
            tuple(sorted(self._versions.items())),
        )

    async def _persist_registry(self, force: bool = False):
        fingerprint = self._registry_fingerprint()
        if not force and fingerprint == self._registry_snapshot:
            return
        import cloudpickle

        import ray_tpu

        blob = cloudpickle.dumps({
            "replicas": {
                app: {dep: list(handles) for dep, handles in deps.items()}
                for app, deps in self._replicas.items()
            },
            "proxies": dict(self._proxies),
            "versions": dict(self._versions),
        })
        w = ray_tpu.global_worker()
        await self._kv_io(lambda: w.gcs_kv_put(CONTROLLER_KV_NS, REGISTRY_KEY, blob))
        self._registry_snapshot = fingerprint

    async def _persist_autopilot(self):
        if self._autopilot is None:
            return
        import cloudpickle

        import ray_tpu

        blob = cloudpickle.dumps(self._autopilot.dump())
        w = ray_tpu.global_worker()
        await self._kv_io(
            lambda: w.gcs_kv_put(CONTROLLER_KV_NS, AUTOPILOT_KEY, blob)
        )
        self._autopilot.mark_clean()

    async def _clear_persisted_state(self):
        import ray_tpu

        w = ray_tpu.global_worker()
        for key in (TARGET_STATE_KEY, REGISTRY_KEY, AUTOPILOT_KEY):
            try:
                await self._kv_io(
                    lambda k=key: w.gcs_call("kv_del", CONTROLLER_KV_NS, k)
                )
            except Exception:
                pass  # GCS down during teardown: stale keys are cleared by
                # the driver-side serve.shutdown() fallback kv_del
        self._registry_snapshot = None

    async def health(self) -> dict:
        """Liveness + identity probe (chaos tests SIGKILL the controller by
        pid and wait for a new incarnation to answer from a different one)."""
        import os

        await self._ensure_recovered()
        return {
            "pid": os.getpid(),
            "apps": sorted(self._apps),
            "recovered": self._recovered,
        }

    # -- proxies -----------------------------------------------------------
    async def ensure_proxies(self, http_options: Optional[dict] = None) -> int:
        """Arm per-node proxy management and return the head node's proxy port.

        Explicit options always take effect: serve.run()/get_proxy_port() arm the
        defaults with {}, and a later serve.start(http_options={'port': N}) must
        not be silently ignored — a port change restarts the proxies."""
        await self._ensure_recovered()
        # Option merge + port-change restart must happen under the same lock
        # as reconciliation: an in-flight reconcile may be about to register a
        # proxy started with the OLD port, and a kill/clear outside the lock
        # would miss it, leaving a stale-port proxy in the table.
        async with self._proxy_lock:
            if http_options:
                prev = self._http_options
                self._http_options = {**(prev or {}), **http_options}
                changed = prev is not None and any(
                    prev.get(k) != self._http_options.get(k)
                    for k in ("port", "grpc_port")
                )
                if changed:
                    for _nid, (handle, _port) in list(self._proxies.items()):
                        self._kill(handle)
                    self._proxies.clear()
                await self._persist_state()
            elif self._http_options is None:
                self._http_options = {}
                await self._persist_state()
            await self._reconcile_proxies_locked()  # raylint: disable=RL905 (proxy reconciliation is deliberately lock-serialized: two interleaved reconciles would double-start proxies on the same node)
        await self._persist_registry()
        import ray_tpu

        head_hex = next(
            (n["node_id"].hex() for n in ray_tpu.nodes() if n.get("is_head")), None
        )
        if head_hex and head_hex in self._proxies:
            return self._proxies[head_hex][1]
        return next(iter(self._proxies.values()))[1] if self._proxies else 0

    async def proxy_ports(self) -> Dict[str, int]:
        await self._ensure_recovered()
        return {nid: port for nid, (_h, port) in self._proxies.items()}

    async def _reconcile_proxies(self):
        if self._http_options is None:
            return
        async with self._proxy_lock:
            await self._reconcile_proxies_locked()  # raylint: disable=RL905 (proxy reconciliation is deliberately lock-serialized: two interleaved reconciles would double-start proxies on the same node)

    async def _reconcile_proxies_locked(self):
        import ray_tpu
        from ray_tpu.serve._common import SERVE_NAMESPACE, async_get
        from ray_tpu.serve._proxy import HTTPProxy
        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

        alive = {n["node_id"].hex(): n for n in ray_tpu.nodes() if n["alive"]}
        # Drop proxies on dead nodes.
        for nid in list(self._proxies):
            if nid not in alive:
                handle, _port = self._proxies.pop(nid)
                self._kill(handle)
        # One proxy per alive node, every node offered the SAME configured port
        # (reference operating model: "any node, one port", proxy.py:706). On a
        # single-host test cluster the extra binds collide and the proxy falls
        # back to an ephemeral port (see HTTPProxy.start).
        for nid, info in alive.items():
            if nid in self._proxies:
                continue
            from ray_tpu._private.config import CONFIG

            port = self._http_options.get("port", CONFIG.serve_http_port)
            host = self._http_options.get("host", "127.0.0.1")
            grpc_port = self._http_options.get("grpc_port")
            proxy_cls = ray_tpu.remote(num_cpus=0)(HTTPProxy)
            try:
                proxy = proxy_cls.options(
                    name=f"SERVE_PROXY:{nid[:12]}", namespace=SERVE_NAMESPACE,
                    get_if_exists=True, max_concurrency=1000,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        info["node_id"], soft=False
                    ),
                ).remote(host, port, grpc_port)
                bound = await async_get(proxy.start.remote(), timeout=30)
            except Exception:
                continue  # node may have just died; next pass retries
            self._proxies[nid] = (proxy, bound)

    # -- deploy / teardown -------------------------------------------------
    async def deploy_app(self, app: str, deployments: Dict[str, dict],
                         route_prefix: Optional[str], ingress: str,
                         ingress_streaming: bool = False) -> bool:
        await self._ensure_recovered()
        if route_prefix is not None:
            for other, deps in self._apps.items():
                if other != app and deps.get("__meta__", {}).get("route_prefix") == route_prefix:
                    raise ValueError(
                        f"route_prefix {route_prefix!r} is already used by app "
                        f"{other!r}; pass a distinct route_prefix (or None for "
                        f"handle-only access)"
                    )
        old = self._apps.get(app, {})
        live = self._replicas.setdefault(app, {})
        # Redeploy: replicas built from changed code/args/config are stale — kill
        # them so reconcile rebuilds from the new blobs (a count-only reconcile
        # would happily keep serving the old code). SCALE fields (num_replicas /
        # autoscaling_config) are explicitly not staleness: a declarative
        # re-apply that only edits replica counts scales the live replica set
        # in place via reconcile (reference: lightweight config updates,
        # serve/_private/deployment_state.py).
        import dataclasses as _dc

        def _code_cfg(cfg):
            return _dc.replace(cfg, num_replicas=1, autoscaling_config=None)

        for name, spec in deployments.items():
            if name == "__meta__":
                continue
            prev = old.get(name)
            if prev is not None and (
                prev["target_blob"] != spec["target_blob"]
                or prev["init_blob"] != spec["init_blob"]
                or _code_cfg(prev["config"]) != _code_cfg(spec["config"])
            ):
                for r in live.pop(name, []):
                    self._kill(r)
                self._bump(app, name)
            elif (
                prev is not None
                and "_autoscale_target" in prev
                and spec["config"].autoscaling_config is not None
            ):
                # Same code, declarative re-apply: the autoscaler's earned
                # target survives the replay — `self._apps[app] =
                # deployments` below would otherwise snap the replica count
                # back to the spec's min and re-cold-start the surge
                # capacity (regression: test_serve_autopilot).
                spec["_autoscale_target"] = prev["_autoscale_target"]
        # Deployments dropped from the app entirely.
        for name in list(old):
            if name != "__meta__" and name not in deployments:
                for r in live.pop(name, []):
                    self._kill(r)
                self._mux_ids.pop(f"{app}#{name}", None)
        self._apps[app] = deployments
        meta = self._apps[app].setdefault("__meta__", {})
        meta["route_prefix"] = route_prefix
        meta["ingress"] = ingress
        meta["ingress_streaming"] = ingress_streaming
        # Persist intent BEFORE reconciling: if the controller dies mid-create,
        # the next incarnation re-reads the full target and reconciles toward
        # it (the registry then tells it which replicas already exist).
        await self._persist_state()
        await self._reconcile_app(app)
        await self._persist_registry()
        return True

    async def delete_app(self, app: str) -> bool:
        await self._ensure_recovered()
        self._apps.pop(app, None)
        await self._persist_state()
        for key in [k for k in self._mux_ids if k.startswith(f"{app}#")]:
            self._mux_ids.pop(key, None)
        for replicas in self._replicas.pop(app, {}).values():
            for r in replicas:
                await self._retire(r)
        await self._persist_registry()
        return True

    async def shutdown_serve(self) -> bool:
        # Best-effort recovery first so persisted-but-unloaded apps' replicas
        # are found and killed too; a failed recovery must not block teardown.
        try:
            await self._ensure_recovered()
        except Exception:
            pass  # recovery needs the GCS; shutdown proceeds on memory state
        self._shutting_down = True
        for app in list(self._apps):
            await self.delete_app(app)
        for _nid, (handle, _port) in list(self._proxies.items()):
            self._kill(handle)
        self._proxies.clear()
        self._http_options = None
        # An explicit shutdown is the END of the serve instance: clear the
        # durable state so the next controller starts cold by design.
        await self._clear_persisted_state()
        return True

    def _kill(self, actor):
        import ray_tpu

        try:
            ray_tpu.kill(actor)
        except Exception:
            pass

    async def _notify_retire(self, app: str, name: str, victim):
        """Scale-down prune hook: before the victim actor dies, the app's
        ingress router (DPRouter/PDRouter) is told to drop the victim's
        prefix fingerprints and adapter-residency entries — without this,
        the router keeps routing cache-affine traffic at a corpse until its
        dead-replica pruning notices on a later pick. Best-effort and
        duck-typed: plain apps whose ingress has no `retire_replica` simply
        skip it."""
        from ray_tpu.serve._common import async_get

        meta = self._apps.get(app, {}).get("__meta__", {})
        ingress = meta.get("ingress")
        if not ingress or ingress == name:
            return
        routers = self._replicas.get(app, {}).get(ingress, [])
        refs = [
            r.handle_request.remote("retire_replica", (victim._actor_id,), {})
            for r in routers
        ]
        for ref in refs:
            try:
                await async_get(ref, timeout=2)
            except Exception:
                pass  # no hook on this ingress (or it is mid-restart)

    async def _retire(self, actor):
        """Graceful replica retirement (delete/scale-down path): give the
        wrapped instance's shutdown() hook a bounded chance to release
        cross-process resources — dp rank tokens, engine steppers, stream
        pumps — before the hard kill reclaims the process. Dead-replica and
        stale-redeploy kills stay on the fast `_kill` path: those replicas
        are gone or about to be replaced wholesale."""
        from ray_tpu.serve._common import async_get

        try:
            await async_get(actor.prepare_shutdown.remote(), timeout=2)
        except Exception:
            pass  # replica dead or unresponsive: the hard kill reclaims it
        self._kill(actor)

    # -- routing tables ----------------------------------------------------
    async def get_replicas(self, app: str, deployment: str) -> dict:
        await self._ensure_recovered()
        key = f"{app}#{deployment}"
        return {
            "version": self._versions.get(key, 0),
            "replicas": list(self._replicas.get(app, {}).get(deployment, [])),
            "multiplexed": dict(self._mux_ids.get(key, {})),
            # Lets handles distinguish "app deleted" (stop retrying) from
            # "replicas still starting / controller just recovered" (wait).
            "exists": app in self._apps and deployment in self._apps.get(app, {}),
        }

    async def get_app_meta(self, app: str) -> Optional[dict]:
        await self._ensure_recovered()
        if app not in self._apps:
            return None
        meta = self._apps[app].get("__meta__", {})
        return {"route_prefix": meta.get("route_prefix"),
                "ingress": meta.get("ingress"),
                "ingress_streaming": meta.get("ingress_streaming", False)}

    async def list_apps(self) -> dict:
        await self._ensure_recovered()
        out = {}
        for app, deps in self._apps.items():
            meta = deps.get("__meta__", {})
            out[app] = {
                "route_prefix": meta.get("route_prefix"),
                "ingress": meta.get("ingress"),
                "ingress_streaming": meta.get("ingress_streaming", False),
                "deployments": {
                    name: {
                        "num_replicas": len(self._replicas.get(app, {}).get(name, [])),
                        "target": spec["config"].num_replicas,
                    }
                    for name, spec in deps.items()
                    if name != "__meta__"
                },
            }
        return out

    async def ready(self, app: str) -> bool:
        """All deployments of the app have their target replica count, and each
        replica answers ready()."""
        import ray_tpu
        from ray_tpu.serve._common import async_get

        await self._ensure_recovered()
        deps = self._apps.get(app)
        if deps is None:
            return False
        for name, spec in deps.items():
            if name == "__meta__":
                continue
            want = self._target_replicas(app, name)
            have = self._replicas.get(app, {}).get(name, [])
            if len(have) < want:
                return False
            try:
                await async_get([r.ready.remote() for r in have], timeout=30)
            except Exception:
                return False
        return True

    # -- reconciliation ----------------------------------------------------
    def _target_replicas(self, app: str, name: str) -> int:
        spec = self._apps[app][name]
        cfg = spec["config"]
        # Autopilot-held targets win for managed deployments: they are the
        # closed-loop decision, persisted in their own KV record so neither
        # a controller restart nor a declarative redeploy resets them.
        if self._autopilot is not None:
            from ray_tpu._private.config import CONFIG

            if CONFIG.serve_autopilot:
                target = self._autopilot.target_for(app, name)
                if target is not None and self._autopilot.manages(app, name):
                    return target
        if cfg.autoscaling_config is not None:
            return spec.setdefault("_autoscale_target", cfg.autoscaling_config.min_replicas)
        return cfg.num_replicas

    async def _reconcile_app(self, app: str):
        import ray_tpu
        from ray_tpu.serve._replica import Replica

        deps = self._apps.get(app, {})
        live = self._replicas.setdefault(app, {})
        for name, spec in list(deps.items()):
            if name == "__meta__":
                continue
            cfg = spec["config"]
            replicas = live.setdefault(name, [])
            # Drop dead replicas (ping failed in the control loop marks them).
            dead = spec.pop("_dead", [])
            if dead:
                keep = []
                for r in replicas:
                    if any(r._actor_id == d for d in dead):
                        self._kill(r)
                    else:
                        keep.append(r)
                live[name] = replicas = keep
            want = self._target_replicas(app, name)
            actor_opts = dict(cfg.ray_actor_options or {})
            actor_opts.setdefault("num_cpus", 0)
            actor_cls = ray_tpu.remote(**actor_opts)(Replica)
            while len(replicas) < want:
                replicas.append(
                    actor_cls.options(max_concurrency=cfg.max_ongoing_requests).remote(
                        spec["target_blob"], spec["init_blob"], name, app,
                        cfg.user_config,
                    )
                )
                self._bump(app, name)
            while len(replicas) > want:
                victim = replicas.pop()
                await self._notify_retire(app, name, victim)
                await self._retire(victim)
                self._bump(app, name)

    def _bump(self, app: str, name: str):
        key = f"{app}#{name}"
        self._versions[key] = self._versions.get(key, 0) + 1

    # -- control loop ------------------------------------------------------
    async def run_control_loop(self):
        if self._loop_started:
            return
        self._loop_started = True
        while not self._shutting_down:
            try:
                # Recovery first (idempotent): the loop may be the only caller
                # on a restarted controller. A GCS outage makes _step raise
                # ConnectionLost after the rpc deadline — caught here, retried
                # next tick; live replicas keep serving off routers' cached
                # tables in the meantime.
                await self._ensure_recovered()
                await self._step()
                if self._state_dirty:
                    await self._persist_state()
                await self._persist_registry()
            except Exception:
                traceback.print_exc()
            from ray_tpu._private.config import CONFIG

            await asyncio.sleep(CONFIG.serve_control_loop_interval_s)

    async def _step(self):
        from ray_tpu.serve._common import async_get

        for app in list(self._apps):
            deps = self._apps.get(app, {})
            for name, spec in list(deps.items()):
                if name == "__meta__":
                    continue
                replicas = self._replicas.get(app, {}).get(name, [])
                # Health check + stats, probed CONCURRENTLY (a serial 5s timeout
                # per starting replica would stall the whole control loop).
                # A replica that has never responded is STARTING (model
                # load/compile can take minutes) and gets a grace period; a
                # replica whose ACTOR DIED is dead immediately; a
                # previously-healthy one that stops answering is dead too.
                health = self._health.setdefault((app, name), {
                    "healthy": set(), "created": {},
                })
                live_ids = {r._actor_id for r in replicas}
                health["healthy"] &= live_ids
                health["created"] = {
                    k: v for k, v in health["created"].items() if k in live_ids
                }
                now = time.monotonic()
                grace_s = 600.0
                for r in replicas:
                    health["created"].setdefault(r._actor_id, now)

                async def probe(r):
                    try:
                        return await async_get(r.get_stats.remote(), timeout=5)
                    except Exception as e:
                        return e

                results = await asyncio.gather(*(probe(r) for r in replicas))
                stats = []
                dead = []
                mux_ids: Dict[Any, list] = {}
                for r, res in zip(replicas, results):
                    if not isinstance(res, Exception):
                        stats.append(res)
                        health["healthy"].add(r._actor_id)
                        ids = res.get("multiplexed_ids") or []
                        if ids:
                            mux_ids[r._actor_id] = list(ids)
                        continue
                    died = type(res).__name__ == "ActorDiedError"
                    started = health["created"].get(r._actor_id, now)
                    if (
                        died
                        or r._actor_id in health["healthy"]
                        or now - started > grace_s
                    ):
                        dead.append(r._actor_id)
                if dead:
                    spec["_dead"] = dead
                # Cluster-wide multiplex view: replicas report loaded model ids
                # through get_stats; routers prefer replicas that already hold
                # the model (reference routes on replica-reported ids,
                # python/ray/serve/multiplex.py).
                self._mux_ids[f"{app}#{name}"] = mux_ids
                cfg = spec["config"]
                # The legacy ongoing-requests autoscaler stands down for
                # autopilot-managed deployments: two laws writing one
                # target would fight.
                if cfg.autoscaling_config is not None and stats and not (
                    self._autopilot is not None
                    and self._autopilot.manages(app, name)
                ):
                    self._autoscale(app, name, spec, stats)
            await self._reconcile_app(app)
        await self._maybe_autopilot()
        await self._reconcile_proxies()

    def _autoscale(self, app: str, name: str, spec: dict, stats: List[dict]):
        cfg = spec["config"].autoscaling_config
        total_ongoing = sum(s["ongoing"] for s in stats)
        current = spec.get("_autoscale_target", cfg.min_replicas)
        desired = max(
            cfg.min_replicas,
            min(cfg.max_replicas, math.ceil(total_ongoing / cfg.target_ongoing_requests)),
        )
        now = time.monotonic()
        key = (app, name)
        last = self._last_scale.get(key, 0.0)
        if desired > current and now - last >= cfg.upscale_delay_s:
            spec["_autoscale_target"] = desired
            self._last_scale[key] = now
            self._state_dirty = True  # autoscale target is declarative state
        elif desired < current and now - last >= cfg.downscale_delay_s:
            spec["_autoscale_target"] = current - 1  # scale down gently
            self._last_scale[key] = now
            self._state_dirty = True

    # -- SLO autopilot (docs/autoscale.md) ---------------------------------
    def _ensure_autopilot(self):
        if self._autopilot is None:
            from ray_tpu._private.config import CONFIG
            from ray_tpu.serve.autopilot import Autopilot

            self._autopilot = Autopilot(
                decision_log_cap=CONFIG.serve_autopilot_decision_log)
        return self._autopilot

    def _autopilot_bounds(self, spec: dict):
        """Per-deployment scaling bounds: the deployment's own
        AutoscalingConfig min/max win when set; the serve_autopilot_* flags
        are the fleet default. Timing knobs always come from the flags."""
        from ray_tpu._private.config import CONFIG
        from ray_tpu.serve.autopilot import ReplicaBounds

        ac = spec["config"].autoscaling_config
        return ReplicaBounds(
            min_replicas=(ac.min_replicas if ac is not None
                          else CONFIG.serve_autopilot_min_replicas),
            max_replicas=(ac.max_replicas if ac is not None
                          else CONFIG.serve_autopilot_max_replicas),
            burn_high=CONFIG.serve_autopilot_burn_high,
            queue_high=CONFIG.serve_autopilot_queue_high,
            sustain_ticks=CONFIG.serve_autopilot_sustain_ticks,
            upscale_cooldown_s=CONFIG.serve_autopilot_upscale_cooldown_s,
            downscale_cooldown_s=CONFIG.serve_autopilot_downscale_cooldown_s,
            cold_start_guard_s=CONFIG.serve_autopilot_cold_start_guard_s,
        )

    async def _autopilot_observe(self) -> list:
        """Probe every replica's `autopilot_signals()` (duck-typed opt-in:
        deployments whose replicas answer become autopilot-managed) and
        fold the answers into per-deployment observations."""
        from ray_tpu.serve._common import async_get
        from ray_tpu.serve.autopilot import aggregate_signals

        probes = []
        for app, deps in list(self._apps.items()):
            for name, spec in list(deps.items()):
                if name == "__meta__":
                    continue
                replicas = self._replicas.get(app, {}).get(name, [])
                if not replicas:
                    continue
                refs = [
                    r.handle_request.remote("autopilot_signals", (), {})
                    for r in replicas
                ]
                probes.append((app, name, spec, len(replicas), refs))
        out = []
        for app, name, spec, n, refs in probes:
            results = await asyncio.gather(
                *(async_get(ref, timeout=5) for ref in refs),
                return_exceptions=True)
            signals = [r for r in results if isinstance(r, dict)]
            if not signals:
                continue  # no replica opted in: not autopilot-managed
            obs = aggregate_signals(app, name, signals)
            obs.replicas = n  # count starting replicas too, not just responders
            obs.bounds = self._autopilot_bounds(spec)
            out.append(obs)
        return out

    async def _maybe_autopilot(self):
        from ray_tpu._private.config import CONFIG

        if not CONFIG.serve_autopilot:
            return
        now = time.time()
        if now - self._autopilot_last < CONFIG.serve_autopilot_interval_s:
            return
        self._autopilot_last = now
        from ray_tpu.serve.autopilot import (
            ScaleAction,
            WeightBounds,
        )

        ap = self._ensure_autopilot()
        observations = await self._autopilot_observe()
        weight_bounds = WeightBounds(
            step=CONFIG.serve_autopilot_weight_step,
            floor=CONFIG.serve_autopilot_weight_floor,
            ceiling=CONFIG.serve_autopilot_weight_max,
            deadband=CONFIG.serve_autopilot_weight_deadband,
            sustain_ticks=CONFIG.serve_autopilot_sustain_ticks,
            cooldown_s=CONFIG.serve_autopilot_upscale_cooldown_s,
        )
        actions = ap.tick(
            observations, weight_bounds,
            pd_ratio_tol=CONFIG.serve_autopilot_pd_ratio_tol, now=now)
        for action in actions:
            if isinstance(action, ScaleAction):
                op = ap.begin_scale_op(action)
                await self._apply_scale_op(op, action.app)
            else:
                await self._broadcast_weight(action)
        if ap.dirty:
            await self._persist_autopilot()

    async def _apply_scale_op(self, op, app: str) -> bool:
        """Actuate one replica-count change under its two-phase token: the
        reconcile either lands (commit) or the autopilot's target rolls
        back to what the cluster actually has (abort) — a failed scale-up
        must not persist a phantom target that respawns forever."""
        try:
            await self._reconcile_app(app)
            await self._persist_registry()
        except Exception:
            traceback.print_exc()
            op.abort()
            return False
        op.commit()
        return True

    async def _broadcast_weight(self, action) -> None:
        """Push one tenant's adapted WFQ weight to every managed replica of
        the app (the engine forwards to its scheduler's weighted-fair
        queues; DPRouter fans out to DP ranks)."""
        from ray_tpu.serve._common import async_get

        refs = []
        for name in list(self._apps.get(action.app, {})):
            if name == "__meta__":
                continue
            if not (self._autopilot is not None
                    and self._autopilot.manages(action.app, name)):
                continue
            for r in self._replicas.get(action.app, {}).get(name, []):
                refs.append(r.handle_request.remote(
                    "set_tenant_weight", (action.tenant, action.weight), {}))
        applied = 0
        for ref in refs:
            try:
                await async_get(ref, timeout=5)
                applied += 1
            except Exception:
                pass  # replica died or lacks the hook: next tick re-nudges
        action.decision["outcome"] = (
            f"applied:{applied}/{len(refs)}" if refs else "no_replicas")

    async def autopilot_wake(self, app: str, deployment: str) -> bool:
        """Scale-to-zero cold start: a deployment handle found zero
        replicas for an existing deployment. Bypasses pressure hysteresis
        (the requester is already waiting) and arms the cold-start guard so
        the fresh replica is not retired straight back to zero."""
        from ray_tpu._private.config import CONFIG

        await self._ensure_recovered()
        if not CONFIG.serve_autopilot:
            return False
        spec = self._apps.get(app, {}).get(deployment)
        if spec is None or deployment == "__meta__":
            return False
        key = f"{app}#{deployment}"
        now = time.monotonic()
        # A fleet of handles stampeding the same cold deployment collapses
        # to one wake per second.
        if now - self._autopilot_wake_ts.get(key, -1e9) < 1.0:
            return False
        self._autopilot_wake_ts[key] = now
        ap = self._ensure_autopilot()
        action = ap.wake(app, deployment, self._autopilot_bounds(spec))
        if action is None:
            return False
        op = ap.begin_scale_op(action)
        ok = await self._apply_scale_op(op, app)
        await self._persist_autopilot()
        return ok

    async def autopilot_stats(self) -> dict:
        """Report surface for serve_stats()/`ray_tpu status`: the decision
        log, autopilot-held targets, and adapted tenant weights. This is
        also where the autopilot's own metrics flush (report path)."""
        from ray_tpu._private.config import CONFIG

        await self._ensure_recovered()
        out = {"enabled": bool(CONFIG.serve_autopilot)}
        if self._autopilot is not None:
            out.update(self._autopilot.stats())
        return out
