"""ServeController: the serve control plane actor.

Design parity: reference `python/ray/serve/_private/controller.py` (:103) +
`application_state.py` + `deployment_state.py` — hold the desired state (apps →
deployments → configs), reconcile replica actors toward it (create missing, kill
excess, replace dead), serve routing tables to handles, and run the autoscaling
policy over replica stats (`autoscaling_policy.py`).
"""

from __future__ import annotations

import asyncio
import math
import time
import traceback
from typing import Any, Dict, List, Optional


class ServeController:
    """Async actor. One per cluster, named SERVE_CONTROLLER in the serve namespace."""

    def __init__(self):
        # app -> deployment -> spec dict (blobs + DeploymentConfig)
        self._apps: Dict[str, Dict[str, dict]] = {}
        # app -> deployment -> list of replica ActorHandles
        self._replicas: Dict[str, Dict[str, list]] = {}
        self._versions: Dict[str, int] = {}
        self._loop_started = False
        self._shutting_down = False
        # autoscale bookkeeping: (app, dep) -> last scale decision time
        self._last_scale: Dict[tuple, float] = {}
        # health bookkeeping OUTSIDE the spec dicts: redeploys must not reset a
        # live replica's "has been healthy" status or its startup clock.
        # (app, dep) -> {"healthy": set[actor_id], "created": {actor_id: t}}
        self._health: Dict[tuple, dict] = {}
        # Per-node HTTP proxies (reference: one ProxyActor per node, proxy.py):
        # node_id hex -> (actor handle, port). Reconciled against cluster
        # membership in the control loop once ensure_proxies() arms it.
        self._http_options: Optional[dict] = None
        self._proxies: Dict[str, tuple] = {}
        # Serializes proxy reconciliation: concurrent ensure_proxies calls
        # (driver + control loop) must not both create/start the same node's
        # proxy — interleaved starts split the bound-port table.
        self._proxy_lock = asyncio.Lock()
        self._mux_ids: Dict[str, dict] = {}  # "app#dep" -> {actor_id: [model ids]}

    # -- proxies -----------------------------------------------------------
    async def ensure_proxies(self, http_options: Optional[dict] = None) -> int:
        """Arm per-node proxy management and return the head node's proxy port.

        Explicit options always take effect: serve.run()/get_proxy_port() arm the
        defaults with {}, and a later serve.start(http_options={'port': N}) must
        not be silently ignored — a port change restarts the proxies."""
        # Option merge + port-change restart must happen under the same lock
        # as reconciliation: an in-flight reconcile may be about to register a
        # proxy started with the OLD port, and a kill/clear outside the lock
        # would miss it, leaving a stale-port proxy in the table.
        async with self._proxy_lock:
            if http_options:
                prev = self._http_options
                self._http_options = {**(prev or {}), **http_options}
                changed = prev is not None and any(
                    prev.get(k) != self._http_options.get(k)
                    for k in ("port", "grpc_port")
                )
                if changed:
                    for _nid, (handle, _port) in list(self._proxies.items()):
                        self._kill(handle)
                    self._proxies.clear()
            elif self._http_options is None:
                self._http_options = {}
            await self._reconcile_proxies_locked()
        import ray_tpu

        head_hex = next(
            (n["node_id"].hex() for n in ray_tpu.nodes() if n.get("is_head")), None
        )
        if head_hex and head_hex in self._proxies:
            return self._proxies[head_hex][1]
        return next(iter(self._proxies.values()))[1] if self._proxies else 0

    async def proxy_ports(self) -> Dict[str, int]:
        return {nid: port for nid, (_h, port) in self._proxies.items()}

    async def _reconcile_proxies(self):
        if self._http_options is None:
            return
        async with self._proxy_lock:
            await self._reconcile_proxies_locked()

    async def _reconcile_proxies_locked(self):
        import ray_tpu
        from ray_tpu.serve._common import SERVE_NAMESPACE, async_get
        from ray_tpu.serve._proxy import HTTPProxy
        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

        alive = {n["node_id"].hex(): n for n in ray_tpu.nodes() if n["alive"]}
        # Drop proxies on dead nodes.
        for nid in list(self._proxies):
            if nid not in alive:
                handle, _port = self._proxies.pop(nid)
                self._kill(handle)
        # One proxy per alive node, every node offered the SAME configured port
        # (reference operating model: "any node, one port", proxy.py:706). On a
        # single-host test cluster the extra binds collide and the proxy falls
        # back to an ephemeral port (see HTTPProxy.start).
        for nid, info in alive.items():
            if nid in self._proxies:
                continue
            from ray_tpu._private.config import CONFIG

            port = self._http_options.get("port", CONFIG.serve_http_port)
            host = self._http_options.get("host", "127.0.0.1")
            grpc_port = self._http_options.get("grpc_port")
            proxy_cls = ray_tpu.remote(num_cpus=0)(HTTPProxy)
            try:
                proxy = proxy_cls.options(
                    name=f"SERVE_PROXY:{nid[:12]}", namespace=SERVE_NAMESPACE,
                    get_if_exists=True, max_concurrency=1000,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        info["node_id"], soft=False
                    ),
                ).remote(host, port, grpc_port)
                bound = await async_get(proxy.start.remote(), timeout=30)
            except Exception:
                continue  # node may have just died; next pass retries
            self._proxies[nid] = (proxy, bound)

    # -- deploy / teardown -------------------------------------------------
    async def deploy_app(self, app: str, deployments: Dict[str, dict],
                         route_prefix: Optional[str], ingress: str,
                         ingress_streaming: bool = False) -> bool:
        if route_prefix is not None:
            for other, deps in self._apps.items():
                if other != app and deps.get("__meta__", {}).get("route_prefix") == route_prefix:
                    raise ValueError(
                        f"route_prefix {route_prefix!r} is already used by app "
                        f"{other!r}; pass a distinct route_prefix (or None for "
                        f"handle-only access)"
                    )
        old = self._apps.get(app, {})
        live = self._replicas.setdefault(app, {})
        # Redeploy: replicas built from changed code/args/config are stale — kill
        # them so reconcile rebuilds from the new blobs (a count-only reconcile
        # would happily keep serving the old code). SCALE fields (num_replicas /
        # autoscaling_config) are explicitly not staleness: a declarative
        # re-apply that only edits replica counts scales the live replica set
        # in place via reconcile (reference: lightweight config updates,
        # serve/_private/deployment_state.py).
        import dataclasses as _dc

        def _code_cfg(cfg):
            return _dc.replace(cfg, num_replicas=1, autoscaling_config=None)

        for name, spec in deployments.items():
            if name == "__meta__":
                continue
            prev = old.get(name)
            if prev is not None and (
                prev["target_blob"] != spec["target_blob"]
                or prev["init_blob"] != spec["init_blob"]
                or _code_cfg(prev["config"]) != _code_cfg(spec["config"])
            ):
                for r in live.pop(name, []):
                    self._kill(r)
                self._bump(app, name)
        # Deployments dropped from the app entirely.
        for name in list(old):
            if name != "__meta__" and name not in deployments:
                for r in live.pop(name, []):
                    self._kill(r)
                self._mux_ids.pop(f"{app}#{name}", None)
        self._apps[app] = deployments
        meta = self._apps[app].setdefault("__meta__", {})
        meta["route_prefix"] = route_prefix
        meta["ingress"] = ingress
        meta["ingress_streaming"] = ingress_streaming
        await self._reconcile_app(app)
        return True

    async def delete_app(self, app: str) -> bool:
        self._apps.pop(app, None)
        for key in [k for k in self._mux_ids if k.startswith(f"{app}#")]:
            self._mux_ids.pop(key, None)
        for replicas in self._replicas.pop(app, {}).values():
            for r in replicas:
                self._kill(r)
        return True

    async def shutdown_serve(self) -> bool:
        self._shutting_down = True
        for app in list(self._apps):
            await self.delete_app(app)
        for _nid, (handle, _port) in list(self._proxies.items()):
            self._kill(handle)
        self._proxies.clear()
        self._http_options = None
        return True

    def _kill(self, actor):
        import ray_tpu

        try:
            ray_tpu.kill(actor)
        except Exception:
            pass

    # -- routing tables ----------------------------------------------------
    async def get_replicas(self, app: str, deployment: str) -> dict:
        key = f"{app}#{deployment}"
        return {
            "version": self._versions.get(key, 0),
            "replicas": list(self._replicas.get(app, {}).get(deployment, [])),
            "multiplexed": dict(self._mux_ids.get(key, {})),
        }

    async def get_app_meta(self, app: str) -> Optional[dict]:
        if app not in self._apps:
            return None
        meta = self._apps[app].get("__meta__", {})
        return {"route_prefix": meta.get("route_prefix"),
                "ingress": meta.get("ingress"),
                "ingress_streaming": meta.get("ingress_streaming", False)}

    async def list_apps(self) -> dict:
        out = {}
        for app, deps in self._apps.items():
            meta = deps.get("__meta__", {})
            out[app] = {
                "route_prefix": meta.get("route_prefix"),
                "ingress": meta.get("ingress"),
                "ingress_streaming": meta.get("ingress_streaming", False),
                "deployments": {
                    name: {
                        "num_replicas": len(self._replicas.get(app, {}).get(name, [])),
                        "target": spec["config"].num_replicas,
                    }
                    for name, spec in deps.items()
                    if name != "__meta__"
                },
            }
        return out

    async def ready(self, app: str) -> bool:
        """All deployments of the app have their target replica count, and each
        replica answers ready()."""
        import ray_tpu
        from ray_tpu.serve._common import async_get

        deps = self._apps.get(app)
        if deps is None:
            return False
        for name, spec in deps.items():
            if name == "__meta__":
                continue
            want = self._target_replicas(app, name)
            have = self._replicas.get(app, {}).get(name, [])
            if len(have) < want:
                return False
            try:
                await async_get([r.ready.remote() for r in have], timeout=30)
            except Exception:
                return False
        return True

    # -- reconciliation ----------------------------------------------------
    def _target_replicas(self, app: str, name: str) -> int:
        spec = self._apps[app][name]
        cfg = spec["config"]
        if cfg.autoscaling_config is not None:
            return spec.setdefault("_autoscale_target", cfg.autoscaling_config.min_replicas)
        return cfg.num_replicas

    async def _reconcile_app(self, app: str):
        import ray_tpu
        from ray_tpu.serve._replica import Replica

        deps = self._apps.get(app, {})
        live = self._replicas.setdefault(app, {})
        for name, spec in list(deps.items()):
            if name == "__meta__":
                continue
            cfg = spec["config"]
            replicas = live.setdefault(name, [])
            # Drop dead replicas (ping failed in the control loop marks them).
            dead = spec.pop("_dead", [])
            if dead:
                keep = []
                for r in replicas:
                    if any(r._actor_id == d for d in dead):
                        self._kill(r)
                    else:
                        keep.append(r)
                live[name] = replicas = keep
            want = self._target_replicas(app, name)
            actor_opts = dict(cfg.ray_actor_options or {})
            actor_opts.setdefault("num_cpus", 0)
            actor_cls = ray_tpu.remote(**actor_opts)(Replica)
            while len(replicas) < want:
                replicas.append(
                    actor_cls.options(max_concurrency=cfg.max_ongoing_requests).remote(
                        spec["target_blob"], spec["init_blob"], name, app,
                        cfg.user_config,
                    )
                )
                self._bump(app, name)
            while len(replicas) > want:
                victim = replicas.pop()
                self._kill(victim)
                self._bump(app, name)

    def _bump(self, app: str, name: str):
        key = f"{app}#{name}"
        self._versions[key] = self._versions.get(key, 0) + 1

    # -- control loop ------------------------------------------------------
    async def run_control_loop(self):
        if self._loop_started:
            return
        self._loop_started = True
        while not self._shutting_down:
            try:
                await self._step()
            except Exception:
                traceback.print_exc()
            from ray_tpu._private.config import CONFIG

            await asyncio.sleep(CONFIG.serve_control_loop_interval_s)

    async def _step(self):
        from ray_tpu.serve._common import async_get

        for app in list(self._apps):
            deps = self._apps.get(app, {})
            for name, spec in list(deps.items()):
                if name == "__meta__":
                    continue
                replicas = self._replicas.get(app, {}).get(name, [])
                # Health check + stats, probed CONCURRENTLY (a serial 5s timeout
                # per starting replica would stall the whole control loop).
                # A replica that has never responded is STARTING (model
                # load/compile can take minutes) and gets a grace period; a
                # replica whose ACTOR DIED is dead immediately; a
                # previously-healthy one that stops answering is dead too.
                health = self._health.setdefault((app, name), {
                    "healthy": set(), "created": {},
                })
                live_ids = {r._actor_id for r in replicas}
                health["healthy"] &= live_ids
                health["created"] = {
                    k: v for k, v in health["created"].items() if k in live_ids
                }
                now = time.monotonic()
                grace_s = 600.0
                for r in replicas:
                    health["created"].setdefault(r._actor_id, now)

                async def probe(r):
                    try:
                        return await async_get(r.get_stats.remote(), timeout=5)
                    except Exception as e:
                        return e

                results = await asyncio.gather(*(probe(r) for r in replicas))
                stats = []
                dead = []
                mux_ids: Dict[Any, list] = {}
                for r, res in zip(replicas, results):
                    if not isinstance(res, Exception):
                        stats.append(res)
                        health["healthy"].add(r._actor_id)
                        ids = res.get("multiplexed_ids") or []
                        if ids:
                            mux_ids[r._actor_id] = list(ids)
                        continue
                    died = type(res).__name__ == "ActorDiedError"
                    started = health["created"].get(r._actor_id, now)
                    if (
                        died
                        or r._actor_id in health["healthy"]
                        or now - started > grace_s
                    ):
                        dead.append(r._actor_id)
                if dead:
                    spec["_dead"] = dead
                # Cluster-wide multiplex view: replicas report loaded model ids
                # through get_stats; routers prefer replicas that already hold
                # the model (reference routes on replica-reported ids,
                # python/ray/serve/multiplex.py).
                self._mux_ids[f"{app}#{name}"] = mux_ids
                cfg = spec["config"]
                if cfg.autoscaling_config is not None and stats:
                    self._autoscale(app, name, spec, stats)
            await self._reconcile_app(app)
        await self._reconcile_proxies()

    def _autoscale(self, app: str, name: str, spec: dict, stats: List[dict]):
        cfg = spec["config"].autoscaling_config
        total_ongoing = sum(s["ongoing"] for s in stats)
        current = spec.get("_autoscale_target", cfg.min_replicas)
        desired = max(
            cfg.min_replicas,
            min(cfg.max_replicas, math.ceil(total_ongoing / cfg.target_ongoing_requests)),
        )
        now = time.monotonic()
        key = (app, name)
        last = self._last_scale.get(key, 0.0)
        if desired > current and now - last >= cfg.upscale_delay_s:
            spec["_autoscale_target"] = desired
            self._last_scale[key] = now
        elif desired < current and now - last >= cfg.downscale_delay_s:
            spec["_autoscale_target"] = current - 1  # scale down gently
            self._last_scale[key] = now
