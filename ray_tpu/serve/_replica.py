"""Replica: the actor that runs user deployment code.

Design parity: reference `python/ray/serve/_private/replica.py` (`Replica` :1041,
`UserCallableWrapper` :1333) — wraps the user class/function, counts ongoing requests
for the router's load metric and the autoscaler, supports sync and async callables and
method dispatch, reconstructs nested deployment handles for composition, streams
generator responses over the handle (handle_request_streaming), and carries the
multiplexed model id of each request into `serve.get_multiplexed_model_id()`.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any

MUX_KWARG = "_serve_mux_model_id"
# Streaming cancel plane (docs/generation.md): the handle injects a token into
# streaming-call kwargs; an abandoned DeploymentResponseGenerator fires
# cancel_stream(token) and the replica interrupts the endpoint generator so
# its finally-blocks release what they hold (decode slots, leases, pins).
STREAM_CANCEL_KWARG = "_serve_stream_cancel_token"


async def _await_it(awaitable):
    return await awaitable


class Replica:
    """Async actor: one replica of one deployment."""

    def __init__(self, cls_or_fn_blob: bytes, init_args_blob: bytes, deployment: str,
                 app: str, user_config=None):
        import cloudpickle

        target = cloudpickle.loads(cls_or_fn_blob)
        init_args, init_kwargs = cloudpickle.loads(init_args_blob)
        self._deployment = deployment
        self._app = app
        self._ongoing = 0
        self._total = 0
        self._stream_cancels: dict = {}  # cancel token -> asyncio.Event
        if inspect.isclass(target):
            self._instance = target(*init_args, **init_kwargs)
        else:
            # Function deployment: calls dispatch to the function itself.
            self._instance = target
        if user_config is not None and hasattr(self._instance, "reconfigure"):
            out = self._instance.reconfigure(user_config)
            if inspect.isawaitable(out):
                # __init__ runs off the actor's event loop, so a private loop here
                # is safe — and required, or an async reconfigure would silently
                # never run and the initial user_config would be dropped.
                asyncio.run(_await_it(out))

    async def reconfigure(self, user_config):
        out = self._instance.reconfigure(user_config)
        if inspect.isawaitable(out):
            await out
        return True

    async def prepare_shutdown(self) -> bool:
        """Graceful pre-kill hook: run the wrapped instance's `shutdown()`
        (if it defines one) so cross-process resources — dp rank tokens,
        engine stepper threads, stream pumps — release explicitly instead of
        relying on actor-death GC. Best-effort by contract: the controller
        bounds the wait and hard-kills regardless of the outcome."""
        fn = getattr(self._instance, "shutdown", None)
        if fn is None or not callable(fn):
            return False
        out = fn()
        if inspect.isawaitable(out):
            await out
        return True

    async def _resolve_ref_args(self, args: tuple, kwargs: dict):
        """Chained DeploymentResponses arrive as ObjectRefs nested inside the args
        tuple (not top-level task args), so resolve them here — off the event
        loop, since get() blocks."""
        import ray_tpu

        if any(isinstance(a, ray_tpu.ObjectRef) for a in args) or any(
            isinstance(v, ray_tpu.ObjectRef) for v in kwargs.values()
        ):
            loop = asyncio.get_running_loop()
            args, kwargs = await loop.run_in_executor(
                None,
                lambda: (
                    tuple(
                        ray_tpu.get(a) if isinstance(a, ray_tpu.ObjectRef) else a
                        for a in args
                    ),
                    {
                        k: ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v
                        for k, v in kwargs.items()
                    },
                ),
            )
        return args, kwargs

    def _lookup(self, method_name: str):
        if callable(self._instance) and method_name == "__call__":
            return self._instance
        return getattr(self._instance, method_name)

    async def handle_request(self, method_name: str, args: tuple, kwargs: dict) -> Any:
        from ray_tpu.serve.multiplex import _reset_model_id, _set_model_id

        mux_id = kwargs.pop(MUX_KWARG, "")
        self._ongoing += 1
        self._total += 1
        token = _set_model_id(mux_id)
        try:
            args, kwargs = await self._resolve_ref_args(args, kwargs)
            fn = self._lookup(method_name)
            if inspect.iscoroutinefunction(fn) or (
                not inspect.isfunction(fn) and not inspect.ismethod(fn)
                and inspect.iscoroutinefunction(getattr(fn, "__call__", None))
            ):
                out = await fn(*args, **kwargs)
            else:
                # Sync callables run off-loop: a blocking handler must not freeze
                # the replica's event loop (that would serialize all requests and
                # zero out the concurrency the router/autoscaler observe). The
                # model-id contextvar is re-seated inside the pool thread —
                # run_in_executor does not propagate context.
                loop = asyncio.get_running_loop()

                def call_sync():
                    t = _set_model_id(mux_id)
                    try:
                        return fn(*args, **kwargs)
                    finally:
                        _reset_model_id(t)

                out = await loop.run_in_executor(None, call_sync)
            if inspect.isawaitable(out):
                out = await out
            if inspect.isgenerator(out):
                # Non-streaming call of a generator endpoint: materialized.
                # (Streaming consumers use handle.options(stream=True), which
                # routes through handle_request_streaming instead.)
                out = list(out)
            elif inspect.isasyncgen(out):
                items = []
                async for item in out:
                    items.append(item)
                out = items
            return out
        finally:
            _reset_model_id(token)
            self._ongoing -= 1

    async def handle_request_streaming(self, method_name: str, args: tuple, kwargs: dict):
        """Async generator: each item the user endpoint yields streams to the
        caller as soon as it is produced (reference: replica.py generator path
        over the handle; rides the runtime's num_returns="streaming")."""
        from ray_tpu.serve.multiplex import _reset_model_id, _set_model_id

        mux_id = kwargs.pop(MUX_KWARG, "")
        cancel_token = kwargs.pop(STREAM_CANCEL_KWARG, None)
        cancel_ev: "asyncio.Event | None" = None
        if cancel_token is not None:
            cancel_ev = asyncio.Event()
            self._stream_cancels[cancel_token] = cancel_ev
        self._ongoing += 1
        self._total += 1
        token = _set_model_id(mux_id)
        try:
            args, kwargs = await self._resolve_ref_args(args, kwargs)
            fn = self._lookup(method_name)
            if (
                inspect.isgeneratorfunction(fn)
                or inspect.isasyncgenfunction(fn)
                or inspect.iscoroutinefunction(fn)
            ):
                out = fn(*args, **kwargs)
            else:
                # Plain sync callable behind a streaming handle: run it off-loop,
                # same invariant as handle_request (a blocking body must not
                # freeze the replica's event loop).
                loop = asyncio.get_running_loop()

                def call_sync():
                    t = _set_model_id(mux_id)
                    try:
                        return fn(*args, **kwargs)
                    finally:
                        _reset_model_id(t)

                out = await loop.run_in_executor(None, call_sync)
            if inspect.isawaitable(out):
                out = await out
            if inspect.isasyncgen(out):
                if cancel_ev is None:
                    async for item in out:
                        yield item
                else:
                    async for item in self._drive_cancellable(out, cancel_ev):
                        yield item
            elif inspect.isgenerator(out):
                loop = asyncio.get_running_loop()
                done = object()

                def nxt():
                    t = _set_model_id(mux_id)
                    try:
                        return next(out)
                    except StopIteration:
                        return done
                    finally:
                        _reset_model_id(t)

                while True:
                    if cancel_ev is not None and cancel_ev.is_set():
                        out.close()  # run the generator's finally-blocks
                        break
                    item = await loop.run_in_executor(None, nxt)
                    if item is done:
                        break
                    yield item
            else:
                yield out
        finally:
            if cancel_token is not None:
                self._stream_cancels.pop(cancel_token, None)
            _reset_model_id(token)
            self._ongoing -= 1

    @staticmethod
    async def _drive_cancellable(out, cancel_ev: "asyncio.Event"):
        """Drive an async generator, aborting it when cancel_ev fires.

        The abort cancels the in-flight __anext__, so the endpoint generator
        resumes with CancelledError at its await point and its finally-blocks
        run (LLMServer.generate_stream closes its TokenStream there, which
        retires the decode slot within one scheduler iteration)."""
        while True:
            nxt = asyncio.ensure_future(out.__anext__())
            waiter = asyncio.ensure_future(cancel_ev.wait())
            try:
                await asyncio.wait(
                    {nxt, waiter}, return_when=asyncio.FIRST_COMPLETED
                )
            finally:
                waiter.cancel()
            if cancel_ev.is_set() and not nxt.done():
                nxt.cancel()
                try:
                    await nxt
                except (asyncio.CancelledError, StopAsyncIteration):
                    pass
                try:
                    await out.aclose()  # no-op if the cancel already closed it
                except Exception:
                    pass  # the generator's finally already ran on cancel;
                    # a second close failing must not mask the cancel path
                return
            try:
                item = await nxt
            except StopAsyncIteration:
                return
            yield item

    async def cancel_stream(self, token: str) -> bool:
        """Cancel plane for abandoned streams (client disconnect): the handle
        fires this with the token it injected; returns False for unknown /
        already-finished streams (cancel is idempotent and never raises)."""
        ev = self._stream_cancels.get(token)
        if ev is None:
            return False
        ev.set()
        return True

    async def get_stats(self) -> dict:
        import os

        from ray_tpu.serve.multiplex import loaded_model_ids

        return {
            "ongoing": self._ongoing,
            "total": self._total,
            "multiplexed_ids": loaded_model_ids(self._instance),
            # Process identity: chaos/recovery tests assert a recovered
            # controller RE-ADOPTED live replicas (same pids) instead of
            # restarting them.
            "pid": os.getpid(),
        }

    async def ready(self) -> bool:
        return True
