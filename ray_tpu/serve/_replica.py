"""Replica: the actor that runs user deployment code.

Design parity: reference `python/ray/serve/_private/replica.py` (`Replica` :1041,
`UserCallableWrapper` :1333) — wraps the user class/function, counts ongoing requests
for the router's load metric and the autoscaler, supports sync and async callables and
method dispatch, reconstructs nested deployment handles for composition.
"""

from __future__ import annotations

import asyncio
import inspect
import traceback
from typing import Any


async def _await_it(awaitable):
    return await awaitable


class Replica:
    """Async actor: one replica of one deployment."""

    def __init__(self, cls_or_fn_blob: bytes, init_args_blob: bytes, deployment: str,
                 app: str, user_config=None):
        import cloudpickle

        target = cloudpickle.loads(cls_or_fn_blob)
        init_args, init_kwargs = cloudpickle.loads(init_args_blob)
        self._deployment = deployment
        self._app = app
        self._ongoing = 0
        self._total = 0
        if inspect.isclass(target):
            self._instance = target(*init_args, **init_kwargs)
        else:
            # Function deployment: calls dispatch to the function itself.
            self._instance = target
        if user_config is not None and hasattr(self._instance, "reconfigure"):
            out = self._instance.reconfigure(user_config)
            if inspect.isawaitable(out):
                # __init__ runs off the actor's event loop, so a private loop here
                # is safe — and required, or an async reconfigure would silently
                # never run and the initial user_config would be dropped.
                asyncio.run(_await_it(out))

    async def reconfigure(self, user_config):
        out = self._instance.reconfigure(user_config)
        if inspect.isawaitable(out):
            await out
        return True

    async def handle_request(self, method_name: str, args: tuple, kwargs: dict) -> Any:
        import ray_tpu

        self._ongoing += 1
        self._total += 1
        try:
            # Chained DeploymentResponses arrive as ObjectRefs nested inside the
            # args tuple (not top-level task args), so resolve them here — off the
            # event loop, since get() blocks.
            if any(isinstance(a, ray_tpu.ObjectRef) for a in args) or any(
                isinstance(v, ray_tpu.ObjectRef) for v in kwargs.values()
            ):
                loop = asyncio.get_running_loop()
                args, kwargs = await loop.run_in_executor(
                    None,
                    lambda: (
                        tuple(
                            ray_tpu.get(a) if isinstance(a, ray_tpu.ObjectRef) else a
                            for a in args
                        ),
                        {
                            k: ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v
                            for k, v in kwargs.items()
                        },
                    ),
                )
            if callable(self._instance) and method_name == "__call__":
                fn = self._instance
            else:
                fn = getattr(self._instance, method_name)
            if inspect.iscoroutinefunction(fn) or (
                not inspect.isfunction(fn) and not inspect.ismethod(fn)
                and inspect.iscoroutinefunction(getattr(fn, "__call__", None))
            ):
                out = await fn(*args, **kwargs)
            else:
                # Sync callables run off-loop: a blocking handler must not freeze
                # the replica's event loop (that would serialize all requests and
                # zero out the concurrency the router/autoscaler observe).
                loop = asyncio.get_running_loop()
                out = await loop.run_in_executor(None, lambda: fn(*args, **kwargs))
            if inspect.isawaitable(out):
                out = await out
            if inspect.isgenerator(out):
                # Non-streaming v1: generators are materialized. (Reference streams
                # them over the handle; see serve/_private/replica.py generator path.)
                out = list(out)
            elif inspect.isasyncgen(out):
                items = []
                async for item in out:
                    items.append(item)
                out = items
            return out
        finally:
            self._ongoing -= 1

    async def get_stats(self) -> dict:
        return {"ongoing": self._ongoing, "total": self._total}

    async def ready(self) -> bool:
        return True
