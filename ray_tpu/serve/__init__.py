"""ray_tpu.serve: scalable model serving over the distributed runtime.

Parity: reference `python/ray/serve/__init__.py` / `api.py` — @serve.deployment,
Deployment.bind composition, serve.run/delete/shutdown/status, DeploymentHandle,
@serve.batch, HTTP ingress via a proxy actor. TPU-first: replicas are long-lived
actors that hold compiled jitted models warm; @serve.batch keeps the MXU fed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.serve._common import (
    CONTROLLER_KV_NS,
    CONTROLLER_NAME,
    DEFAULT_APP_NAME,
    REGISTRY_KEY,
    SERVE_NAMESPACE,
    TARGET_STATE_KEY,
    AutoscalingConfig,
    ControllerUnavailableError,
    DeploymentConfig,
    DeploymentNotFoundError,
    Request,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed


@dataclass
class Deployment:
    """A deployment definition: user class/function + config. Parity: serve.Deployment."""

    target: Any
    name: str
    config: DeploymentConfig = field(default_factory=DeploymentConfig)

    def options(self, *, name: Optional[str] = None, num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
                ray_actor_options: Optional[dict] = None,
                user_config: Optional[dict] = None) -> "Deployment":
        cfg = replace(self.config)
        if num_replicas is not None:
            if num_replicas == "auto":
                cfg.autoscaling_config = cfg.autoscaling_config or AutoscalingConfig()
            else:
                cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                AutoscalingConfig(**autoscaling_config)
                if isinstance(autoscaling_config, dict)
                else autoscaling_config
            )
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if user_config is not None:
            cfg.user_config = user_config
        return Deployment(self.target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclass
class Application:
    """A bound deployment graph node. Parity: serve.Application (built by .bind())."""

    deployment: Deployment
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)


def deployment(
    _target: Any = None,
    *,
    name: Optional[str] = None,
    num_replicas: Optional[Union[int, str]] = None,
    max_ongoing_requests: int = 100,
    autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
    ray_actor_options: Optional[dict] = None,
    user_config: Optional[dict] = None,
):
    """@serve.deployment decorator. Parity: reference serve/api.py deployment()."""

    def wrap(target):
        cfg = DeploymentConfig(max_ongoing_requests=max_ongoing_requests)
        d = Deployment(target, name or target.__name__, cfg)
        return d.options(
            num_replicas=num_replicas,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
            user_config=user_config,
        )

    if _target is not None:
        return wrap(_target)
    return wrap


def ingress(_app=None):
    """Kept for API parity; the bound top-level deployment is already the ingress."""

    def wrap(cls):
        return cls

    return wrap


# -- controller / proxy lifecycle -----------------------------------------


def _get_or_create_controller():
    from ray_tpu.serve._controller import ServeController

    controller_cls = ray_tpu.remote(num_cpus=0)(ServeController)
    controller = controller_cls.options(
        name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE, get_if_exists=True,
        max_concurrency=1000,
        # The control plane must outlive any single process: unlimited
        # restarts + durable GCS KV state mean a SIGKILLed controller comes
        # back, recovers its app table, and re-adopts live replicas
        # (reference: the serve controller checkpoints to the GCS KV store).
        max_restarts=-1,
    ).remote()
    controller.run_control_loop.remote()  # raylint: disable=RL501 (idempotent fire-and-forget loop start)
    return controller


_proxy_state: dict = {}


def start(http_options: Optional[dict] = None, **_kwargs):
    """Start serve system actors (controller + per-node HTTP proxies).

    Parity: serve.start — the controller owns proxy lifecycle and keeps one
    proxy per alive node (reference: ProxyActor per node, proxy.py:1138); the
    head node's proxy binds the configured port."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _get_or_create_controller()
    if http_options or _proxy_state.get("port") is None:
        port = ray_tpu.get(controller.ensure_proxies.remote(http_options or {}))
        if port:  # 0 = no proxy bound yet; don't cache so callers retry
            _proxy_state["port"] = port
    return controller


def _collect_deployments(app: Application, app_name: str, acc: Dict[str, dict]) -> Any:
    """DFS over the bound graph: nested Applications become DeploymentHandles."""
    import cloudpickle

    d = app.deployment

    def convert(v):
        if isinstance(v, Application):
            return _collect_deployments(v, app_name, acc)
        # Applications may ride inside containers (e.g. a {model_id: app} dict).
        if isinstance(v, dict):
            return {k: convert(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            out = [convert(x) for x in v]
            return tuple(out) if isinstance(v, tuple) else out
        return v

    args = tuple(convert(a) for a in app.init_args)
    kwargs = {k: convert(v) for k, v in app.init_kwargs.items()}
    spec = {
        "target_blob": cloudpickle.dumps(d.target),
        "init_blob": cloudpickle.dumps((args, kwargs)),
        "config": d.config,
    }
    if d.name in acc:
        existing = acc[d.name]
        if (
            existing["target_blob"] != spec["target_blob"]
            or existing["init_blob"] != spec["init_blob"]
            or existing["config"] != spec["config"]
        ):
            raise ValueError(
                f"deployment name {d.name!r} bound twice with different args or "
                f"config; use .options(name=...) to disambiguate"
            )
    acc[d.name] = spec
    return DeploymentHandle(app_name, d.name)


def run(
    app: Application,
    *,
    name: str = DEFAULT_APP_NAME,
    route_prefix: Optional[str] = "/",
    blocking: bool = False,
    _timeout_s: float = 60.0,
) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress. Parity: serve.run.

    route_prefix=None deploys without HTTP exposure (handle-only access).
    """
    from ray_tpu._private import usage_stats

    usage_stats.record_library_usage("serve")
    controller = start()
    acc: Dict[str, dict] = {}
    _collect_deployments(app, name, acc)
    ingress_name = app.deployment.name
    import inspect as _inspect

    target = app.deployment.target
    call = target if not _inspect.isclass(target) else getattr(target, "__call__", None)
    ingress_streaming = bool(
        call is not None
        and (_inspect.isgeneratorfunction(call) or _inspect.isasyncgenfunction(call))
    )
    ray_tpu.get(
        controller.deploy_app.remote(
            name, acc, route_prefix, ingress_name, ingress_streaming
        )
    )
    deadline = time.monotonic() + _timeout_s
    while time.monotonic() < deadline:
        if ray_tpu.get(controller.ready.remote(name)):
            break
        time.sleep(0.1)
    else:
        raise TimeoutError(f"application {name!r} did not become ready")
    handle = DeploymentHandle(name, ingress_name)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def _existing_controller():
    """The live controller, or None — read paths must not spawn one as a side effect."""
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    except Exception:
        return None


def delete(name: str):
    controller = _existing_controller()
    if controller is not None:
        ray_tpu.get(controller.delete_app.remote(name))


def status() -> dict:
    controller = _existing_controller()
    if controller is None:
        return {}
    return ray_tpu.get(controller.list_apps.remote())


def shutdown():
    controller = _existing_controller()
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown_serve.remote(), timeout=15)
        except Exception:
            pass
        try:
            ray_tpu.kill(controller)
        except Exception:
            pass
    # Independent proxy cleanup: a wedged controller must not leak the per-node
    # proxy actors (and their bound ports).
    try:
        for n in ray_tpu.nodes():
            try:
                proxy = ray_tpu.get_actor(
                    f"SERVE_PROXY:{n['node_id'].hex()[:12]}", namespace=SERVE_NAMESPACE
                )
                ray_tpu.kill(proxy)
            except Exception:
                pass
    except Exception:
        pass
    # Independent durable-state cleanup for the same reason: a wedged/dead
    # controller must not leave KV state that resurrects the apps into the
    # NEXT serve instance after an explicit shutdown.
    try:
        w = ray_tpu.global_worker()
        for key in (TARGET_STATE_KEY, REGISTRY_KEY):
            w.gcs_call("kv_del", CONTROLLER_KV_NS, key)
    except Exception:
        pass
    _proxy_state.clear()


def get_app_handle(name: str = DEFAULT_APP_NAME) -> DeploymentHandle:
    controller = _existing_controller()
    meta = (
        ray_tpu.get(controller.get_app_meta.remote(name))
        if controller is not None
        else None
    )
    if meta is None or not meta.get("ingress"):
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(name, meta["ingress"])


def get_deployment_handle(deployment_name: str, app_name: str = DEFAULT_APP_NAME):
    return DeploymentHandle(app_name, deployment_name)


def get_grpc_port() -> Optional[int]:
    """Port of the head node's gRPC ingress (None unless serve.start ran with
    http_options={"grpc_port": N}). Parity: the reference's gRPC proxy."""
    import ray_tpu
    from ray_tpu.serve._common import SERVE_NAMESPACE

    controller = _existing_controller()
    if controller is None:
        return None
    try:
        ports = ray_tpu.get(controller.proxy_ports.remote())
        head_hex = next(
            (n["node_id"].hex() for n in ray_tpu.nodes() if n.get("is_head")), None
        )
        if head_hex is None or head_hex not in ports:
            return None
        proxy = ray_tpu.get_actor(f"SERVE_PROXY:{head_hex[:12]}",
                                  namespace=SERVE_NAMESPACE)
        return ray_tpu.get(proxy.get_grpc_port.remote())
    except Exception:
        return None


def get_proxy_port() -> Optional[int]:
    """Head-node proxy port as ACTUALLY BOUND (the controller's table is fed
    from each proxy's bind result, so port-conflict ephemeral fallback shows
    up here). The driver-side cache is only a fallback when the controller is
    briefly unreachable — it must never shadow the live table."""
    controller = _existing_controller()
    if controller is None:
        return _proxy_state.get("port")
    try:
        port = ray_tpu.get(controller.ensure_proxies.remote(None))
        if port:
            _proxy_state["port"] = port
            return port
        return None
    except Exception:
        return _proxy_state.get("port")


def proxy_ports() -> Dict[str, int]:
    """Per-node proxy ports: node id hex -> bound HTTP port."""
    controller = _existing_controller()
    if controller is None:
        return {}
    return ray_tpu.get(controller.proxy_ports.remote())


__all__ = [
    "Application",
    "AutoscalingConfig",
    "ControllerUnavailableError",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentNotFoundError",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "Request",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "get_grpc_port",
    "get_proxy_port",
    "ingress",
    "multiplexed",
    "proxy_ports",
    "run",
    "shutdown",
    "status",
    "start",
]
