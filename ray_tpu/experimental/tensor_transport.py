"""Tensor-native wire framing: pickled skeleton + raw array payload.

Design parity: reference NIXL/RDT transports move tensor payloads as raw
buffers with a small descriptor (shape/dtype/registration handle) on the
side — serialization frameworks never touch the bytes. Here the same split
is applied to the channel plane: a value's array leaves (numpy / jax) are
lifted out of the object graph, the remaining skeleton is cloudpickled with
tiny ``_Leaf`` placeholders, and one frame carries

    [4B magic "RTF1"][u32 meta_len][meta pickle][64B-aligned payload]

    meta = (skeleton_bytes, [(shape, dtype, payload_offset, nbytes), ...],
            payload_off, total)

so a writer memcpys leaf bytes straight into a shared-memory ring slot (or a
socket) and a reader rebuilds the leaves with ``np.frombuffer`` over the
frame — zero pickle work proportional to tensor size, and optionally zero
copies at all (``copy=False`` aliases the frame buffer; the caller owns the
aliasing lifetime — see docs/device_channels.md for the pin contract).

dtypes travel as ``np.dtype`` objects (not names) so extension dtypes that
jax emits on the host (ml_dtypes bfloat16/float8) round-trip bitwise.
"""

from __future__ import annotations

import pickle
import struct
import sys
import threading
from typing import Any, List, Optional

import cloudpickle
import numpy as np

MAGIC = b"RTF1"
_U32 = struct.Struct("<I")
_ALIGN = 64  # payload alignment: safe for every dtype + vectorized memcpy
_MAX_DEPTH = 8  # container recursion bound (cycles/pathological nests -> pickle)


class _Leaf:
    """Placeholder for an extracted array leaf inside the pickled skeleton."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (_Leaf, (self.index,))


def _as_ndarray(value) -> Optional[np.ndarray]:
    """The host-array view of a tensor leaf, or None if `value` is not one.

    jax arrays are recognized without importing jax (if jax was never
    imported, no jax array can exist); ``np.asarray`` on one is the D2H
    materialization — single-frame writers pay it here, the chunked
    DeviceChannel path slices the transfer instead (device_channel.py)."""
    if isinstance(value, np.ndarray):
        return None if value.dtype.hasobject else value
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(value, jax.Array):
        return np.asarray(value)
    return None


def _split(value, leaves: List[np.ndarray], min_bytes: int, depth: int = 0):
    """Skeleton of `value` with array leaves >= min_bytes replaced by _Leaf."""
    arr = _as_ndarray(value)
    if arr is not None:
        if arr.nbytes < min_bytes:
            return value
        leaves.append(np.ascontiguousarray(arr))
        return _Leaf(len(leaves) - 1)
    if depth >= _MAX_DEPTH:
        return value
    if type(value) is dict:
        return {k: _split(v, leaves, min_bytes, depth + 1)
                for k, v in value.items()}
    if type(value) is list:
        return [_split(v, leaves, min_bytes, depth + 1) for v in value]
    if type(value) is tuple:
        return tuple(_split(v, leaves, min_bytes, depth + 1) for v in value)
    return value


def _join(skeleton, leaves: List[np.ndarray], depth: int = 0):
    if isinstance(skeleton, _Leaf):
        return leaves[skeleton.index]
    if depth >= _MAX_DEPTH:
        return skeleton
    if type(skeleton) is dict:
        return {k: _join(v, leaves, depth + 1) for k, v in skeleton.items()}
    if type(skeleton) is list:
        return [_join(v, leaves, depth + 1) for v in skeleton]
    if type(skeleton) is tuple:
        return tuple(_join(v, leaves, depth + 1) for v in skeleton)
    return skeleton


def as_flat_bytes(arr: np.ndarray) -> np.ndarray:
    """A 1-D uint8 alias of a C-contiguous array's bytes (no copy)."""
    return arr.reshape(-1).view(np.uint8)


class Plan:
    """A sized, ready-to-memcpy tensor frame (header built, leaves staged).

    Built once so transports can check the total against their slot capacity
    BEFORE reserving buffer space, then `write_into` a raw destination."""

    __slots__ = ("meta", "leaves", "descs", "payload_off", "total",
                 "payload_bytes")

    def __init__(self, skeleton_bytes: bytes, leaves: List[np.ndarray]):
        self.leaves = leaves
        self.descs = []
        off = 0
        for arr in leaves:
            self.descs.append((arr.shape, arr.dtype, off, arr.nbytes))
            off += arr.nbytes
        self.payload_bytes = off
        # payload_off is NOT in the meta: both sides derive it from the meta
        # length (align past the header), so the header stays one pickle.
        self.meta = pickle.dumps(
            (skeleton_bytes, self.descs), protocol=pickle.HIGHEST_PROTOCOL
        )
        header_len = len(MAGIC) + _U32.size + len(self.meta)
        self.payload_off = header_len + (-header_len % _ALIGN)
        self.total = self.payload_off + self.payload_bytes

    def write_into(self, buf) -> int:
        """memcpy the frame into a writable buffer; returns bytes written."""
        mv = memoryview(buf)
        mv[0:4] = MAGIC
        _U32.pack_into(mv, 4, len(self.meta))
        mv[8:8 + len(self.meta)] = self.meta
        for arr, (_shape, _dtype, off, nbytes) in zip(self.leaves, self.descs):
            if nbytes:
                dst = self.payload_off + off
                mv[dst:dst + nbytes] = as_flat_bytes(arr).data
        return self.total

    def to_bytes(self) -> bytearray:
        out = bytearray(self.total)
        self.write_into(out)
        return out


def plan(value: Any, min_bytes: int) -> Optional[Plan]:
    """Build a tensor frame plan for `value`, or None when the value has no
    array leaves >= min_bytes (caller falls back to plain pickling).
    min_bytes < 0 disables the fast path entirely."""
    if min_bytes < 0:
        return None
    leaves: List[np.ndarray] = []
    skeleton = _split(value, leaves, max(0, min_bytes))
    if not leaves:
        return None
    skeleton_bytes = cloudpickle.dumps(
        skeleton, protocol=pickle.HIGHEST_PROTOCOL
    )
    return Plan(skeleton_bytes, leaves)


def split(value: Any, min_bytes: int = 0):
    """(skeleton_bytes, leaves) without frame layout — for chunked streams
    (device_channel.py) that frame the payload themselves. Leaves keep their
    original type: jax arrays stay ON DEVICE so the stream writer can slice
    the D2H transfer instead of materializing the whole host copy."""
    leaves: List[Any] = []

    def walk(v, depth=0):
        if _is_leaf(v, min_bytes):
            leaves.append(v)
            return _Leaf(len(leaves) - 1)
        if depth >= _MAX_DEPTH:
            return v
        if type(v) is dict:
            return {k: walk(x, depth + 1) for k, x in v.items()}
        if type(v) is list:
            return [walk(x, depth + 1) for x in v]
        if type(v) is tuple:
            return tuple(walk(x, depth + 1) for x in v)
        return v

    skeleton = walk(value)
    return (
        cloudpickle.dumps(skeleton, protocol=pickle.HIGHEST_PROTOCOL), leaves
    )


def _is_leaf(value, min_bytes: int) -> bool:
    if isinstance(value, np.ndarray):
        return not value.dtype.hasobject and value.nbytes >= min_bytes
    jax = sys.modules.get("jax")
    return (
        jax is not None
        and isinstance(value, jax.Array)
        and value.size * value.dtype.itemsize >= min_bytes
    )


def join(skeleton_bytes: bytes, leaves: List[Any]) -> Any:
    """Inverse of split(): substitute materialized leaves into the skeleton."""
    return _join(cloudpickle.loads(skeleton_bytes), leaves)


def is_frame(buf) -> bool:
    mv = memoryview(buf)
    return len(mv) >= 8 and bytes(mv[0:4]) == MAGIC


def decode(buf, *, copy: bool = True) -> Any:
    """Rebuild the value from a tensor frame.

    copy=True materializes owning arrays (safe when `buf` is a reusable ring
    slot). copy=False aliases `buf` — zero-copy, read-only when `buf` is, and
    only valid while the caller keeps the underlying buffer pinned."""
    mv = memoryview(buf)
    (meta_len,) = _U32.unpack_from(mv, 4)
    skeleton_bytes, descs = pickle.loads(mv[8:8 + meta_len])
    header_len = 8 + meta_len
    payload_off = header_len + (-header_len % _ALIGN)
    leaves = []
    for shape, dtype, off, nbytes in descs:
        src = payload_off + off
        arr = np.frombuffer(mv[src:src + nbytes], dtype=dtype)
        arr = arr.reshape(shape)
        leaves.append(arr.copy() if copy else arr)
    return _join(cloudpickle.loads(skeleton_bytes), leaves)


# -- per-process transport accounting ---------------------------------------
# Tests and CompiledDAG introspection read these to prove array payloads rode
# the raw-buffer path (no cloudpickle of tensor bytes); util.metrics export
# happens at the channel layer, which also feeds these.
_stats_lock = threading.Lock()
_stats = {
    "tensor_frames_written": 0,
    "tensor_frames_read": 0,
    "tensor_bytes_written": 0,
    "pickle_frames_written": 0,
    "pickle_frames_read": 0,
    # One count per payload chunk STAGED OUT of the source array by a
    # DeviceChannel writer (the D2H leg on real accelerators). Multicast
    # fanout writes each staged chunk once for N subscribers, so this is the
    # counter that proves "one D2H pass" (docs/device_channels.md).
    "stream_chunks_staged": 0,
}


def note(key: str, n: int = 1):
    with _stats_lock:
        _stats[key] += n


def transport_stats() -> dict:
    with _stats_lock:
        return dict(_stats)


def reset_transport_stats():
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0
