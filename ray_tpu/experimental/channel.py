"""Mutable shared-memory channels: the compiled-graph transport.

Design parity: reference `python/ray/experimental/channel/shared_memory_channel.py`
(:151 Channel over mutable plasma objects, write :435 / read :473, BufferedSharedMemory
variant :586) and the C++ mutable object manager
(`src/ray/core_worker/experimental_mutable_object_manager.h:44`) — repeated in-place
writes with writer/reader version synchronization, so a compiled DAG reuses a fixed
ring of buffers per edge instead of allocating an object per call.

Segment layout (S slots, R readers):
    [u64 write_version][u64 closed][u64 ack_version x R][u64 len x S][S x payload]
Ring protocol: writer waits until write_version - min(acks) < S (a free slot exists),
writes slot write_version % S, publishes write_version+1. Reader waits until
write_version > my_ack, reads slot my_ack % S, publishes my_ack+1. close() sets the
closed word: BOTH sides observe it from their wait loops (a writer blocked on a full
ring must be stoppable too) and raise ChannelClosed; readers drain buffered values
first. Synchronization is version-polling over shm words (cross-process, nothing to
leak); waits back off to 50us sleeps.

Tensor fast path (round 11, docs/device_channels.md): values whose array
leaves clear `channel_tensor_min_bytes` skip cloudpickle for the payload —
write() memcpys a tensor frame (tensor_transport.py: small pickled header +
raw leaf bytes) straight into the ring slot, read() rebuilds the arrays with
np.frombuffer over the slot, and read_view() hands out a ZERO-COPY lease on
the slot (the ack publishes at release, so the writer cannot recycle the
bytes under a live view — holding a lease back-pressures the ring, it never
corrupts it).
"""

from __future__ import annotations

import pickle
import struct
import threading
import time
import uuid
from multiprocessing import shared_memory
from typing import Any, Optional

import cloudpickle

from ray_tpu.devtools import leaksan as _leaksan
from ray_tpu.experimental import tensor_transport as _tt

_U64 = struct.Struct("<Q")


class ChannelClosed(Exception):
    pass


def _tensor_min_bytes() -> int:
    from ray_tpu._private.config import CONFIG

    return CONFIG.channel_tensor_min_bytes


_chan_metrics: dict = {}
_chan_metrics_lock = threading.Lock()


def _metric(name: str):
    """Lazy channel-plane metrics (util.metrics): created on first use so
    processes that never touch channels pay nothing; flushing is best-effort
    inside the Metric itself (never breaks the transport)."""
    with _chan_metrics_lock:
        m = _chan_metrics.get(name)
        if m is None:
            from ray_tpu.util import metrics

            if name == "chan_bytes_written":
                m = metrics.Counter(
                    "chan_bytes_written",
                    "payload bytes written into compiled-graph/device "
                    "channels",
                )
            else:
                m = metrics.Counter(
                    "chan_tensor_fastpath_total",
                    "channel frames that rode the tensor-native raw-buffer "
                    "path (array payloads not cloudpickled)",
                )
            _chan_metrics[name] = m
        return m


def _note_write(nbytes: int, tensor: bool):
    try:
        _metric("chan_bytes_written").inc(nbytes)
        if tensor:
            _metric("chan_tensor_fastpath_total").inc()
    except Exception:
        pass  # observability must never break the transport
    if tensor:
        _tt.note("tensor_frames_written")
        _tt.note("tensor_bytes_written", nbytes)
    else:
        _tt.note("pickle_frames_written")


class SlotView:
    """A zero-copy lease on one ring slot's frame bytes.

    The reader's ack is published at release(): until then the writer cannot
    recycle the slot, so `mv` (and any np.frombuffer alias of it) stays
    valid. Not releasing a lease blocks the writer on a full ring — the
    contract is back-pressure, never corruption (docs/device_channels.md)."""

    __slots__ = ("mv", "_release", "__weakref__")

    def __init__(self, mv, release):
        self.mv = mv
        self._release = release
        _leaksan.track("slot_view", self, detail=f"{len(mv)}B ring-slot lease")

    def release(self):
        rel, self._release = self._release, None
        if rel is not None:
            try:
                self.mv.release()
            except (BufferError, AttributeError):
                pass  # caller still aliases the slot bytes; their export holds
            self.mv = None
            rel()
            _leaksan.untrack("slot_view", self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


# Segment names created by THIS process: attach-views of these must not unregister
# them from resource_tracker (that would strip the creator's own registration and
# the eventual unlink would traceback in the tracker daemon).
_created_here: set = set()


class Channel:
    """One writer, `num_readers` readers, `num_slots` in-flight values.
    Picklable by segment name; `reader(slot)` binds a reader view."""

    def __init__(self, capacity: int = 4 << 20, num_readers: int = 1,
                 num_slots: Optional[int] = None, _name: Optional[str] = None,
                 _reader_slot: Optional[int] = None):
        if num_slots is None:
            from ray_tpu._private.config import CONFIG

            num_slots = CONFIG.channel_default_slots
        self._capacity = capacity
        self._num_readers = num_readers
        self._num_slots = num_slots
        self._reader_slot = _reader_slot
        self._ctrl = 16 + 8 * num_readers + 8 * num_slots
        total = self._ctrl + num_slots * capacity
        if _name is None:
            self._shm = shared_memory.SharedMemory(
                create=True, size=total, name=f"rtpuch_{uuid.uuid4().hex[:12]}"
            )
            self._owner = True
            self._shm.buf[: self._ctrl] = bytes(self._ctrl)
            with _registry_lock:
                _created_here.add(self._shm.name)
        else:
            self._shm = shared_memory.SharedMemory(name=_name)
            self._owner = False
            # Only the creator owns the segment's lifetime; detach this attachment
            # from resource_tracker or it double-unlinks at exit (CPython gh-82300).
            # Views inside the creator process keep the registration.
            if self._shm.name not in _created_here:
                try:
                    from multiprocessing import resource_tracker

                    resource_tracker.unregister(self._shm._name, "shared_memory")
                except Exception:
                    pass

    # -- pickling ----------------------------------------------------------
    def __reduce__(self):
        return (
            Channel,
            (self._capacity, self._num_readers, self._num_slots, self._shm.name,
             self._reader_slot),
        )

    def reader(self, slot: int) -> "Channel":
        """A view of this channel bound to reader slot `slot`."""
        return Channel(self._capacity, self._num_readers, self._num_slots,
                       self._shm.name, slot)

    # -- control words -----------------------------------------------------
    def _get_u64(self, off: int) -> int:
        return _U64.unpack_from(self._shm.buf, off)[0]

    def _set_u64(self, off: int, value: int):
        _U64.pack_into(self._shm.buf, off, value)

    @property
    def _write_version(self) -> int:
        return self._get_u64(0)

    @property
    def _closed(self) -> bool:
        return self._get_u64(8) != 0

    def _ack_off(self, reader: int) -> int:
        return 16 + 8 * reader

    def _len_off(self, slot: int) -> int:
        return 16 + 8 * self._num_readers + 8 * slot

    def _data_off(self, slot: int) -> int:
        return self._ctrl + slot * self._capacity

    #: Ack value that marks a reader slot as DETACHED: far above any
    #: reachable write_version, so _min_ack (and drain) stop waiting on it.
    _DETACHED_ACK = 1 << 62

    def _min_ack(self) -> int:
        return min(
            self._get_u64(self._ack_off(r)) for r in range(self._num_readers)
        )

    def detach_reader(self, reader: int):
        """Stop counting `reader` toward ring back-pressure (multicast
        dead-subscriber unwind, docs/device_channels.md): its ack word jumps
        past every reachable write version, so a blocked writer resumes and
        the REMAINING readers keep streaming. Callable from any attached
        process (the ack word lives in the shared segment); irreversible for
        this stream — a detached subscriber that polls again reads garbage
        ordering, so callers drop their view after detaching."""
        if not 0 <= reader < self._num_readers:
            raise ValueError(f"reader {reader} out of range")
        self._set_u64(self._ack_off(reader), self._DETACHED_ACK)

    def lagging_readers(self):
        """Reader slots currently holding the ring back (ack == min ack and
        not detached) — the writer's dead-subscriber suspects on a stalled
        multicast write."""
        m = self._min_ack()
        return [
            r for r in range(self._num_readers)
            if self._get_u64(self._ack_off(r)) == m and m < self._DETACHED_ACK
        ]

    # -- writer ------------------------------------------------------------
    def write(self, value: Any, timeout: Optional[float] = None):
        plan = _tt.plan(value, _tensor_min_bytes())
        if plan is None:
            data = cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
            self.write_bytes(data, timeout)
            return
        # Tensor fast path: the frame is memcpy'd straight into the ring slot
        # — array bytes are never cloudpickled and never pass through an
        # intermediate bytes object.
        wv = self._acquire_slot(plan.total, timeout)
        slot = wv % self._num_slots
        off = self._data_off(slot)
        plan.write_into(self._shm.buf[off : off + plan.total])
        self._set_u64(self._len_off(slot), plan.total)
        self._set_u64(0, wv + 1)
        _note_write(plan.total, tensor=True)

    def _acquire_slot(self, need: int, timeout: Optional[float]) -> int:
        """Wait for a free ring slot; returns the write version to fill."""
        if need > self._capacity:
            raise ValueError(
                f"value of {need} bytes exceeds channel slot capacity "
                f"{self._capacity}; construct the Channel with a larger capacity"
            )
        if self._closed:
            raise ChannelClosed()
        wv = self._write_version
        deadline = None if timeout is None else time.monotonic() + timeout
        # Wait for a free slot: slowest reader must be < num_slots behind.
        while wv - self._min_ack() >= self._num_slots:
            if self._closed:
                raise ChannelClosed()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel write timed out waiting for readers")
            time.sleep(5e-5)
        return wv

    def write_bytes(self, data, timeout: Optional[float] = None):
        wv = self._acquire_slot(len(data), timeout)
        slot = wv % self._num_slots
        off = self._data_off(slot)
        self._shm.buf[off : off + len(data)] = data
        self._set_u64(self._len_off(slot), len(data))
        self._set_u64(0, wv + 1)
        _note_write(len(data), tensor=False)

    # -- reader ------------------------------------------------------------
    def _wait_readable(self, timeout: Optional[float]):
        """Block until the next unread item exists; returns (reader, my_ack,
        slot byte offset, item length). The ack is NOT published here."""
        reader = self._reader_slot or 0
        my_ack = self._get_u64(self._ack_off(reader))
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._write_version <= my_ack:
            if self._closed:
                # Buffered values are drained above; nothing more is coming.
                raise ChannelClosed()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel read timed out")
            time.sleep(5e-5)
        slot = my_ack % self._num_slots
        n = self._get_u64(self._len_off(slot))
        return reader, my_ack, self._data_off(slot), n

    def read(self, timeout: Optional[float] = None) -> Any:
        reader, my_ack, off, n = self._wait_readable(timeout)
        view = self._shm.buf[off : off + n]
        if _tt.is_frame(view):
            # Decode arrays directly off the slot (no intermediate bytes
            # object); copy=True because the ack below lets the writer
            # recycle the slot — read_view() is the zero-copy variant.
            value = _tt.decode(view, copy=True)
            self._set_u64(self._ack_off(reader), my_ack + 1)
            _tt.note("tensor_frames_read")
            return value
        data = bytes(view)
        self._set_u64(self._ack_off(reader), my_ack + 1)
        _tt.note("pickle_frames_read")
        return cloudpickle.loads(data)

    def read_bytes(self, timeout: Optional[float] = None) -> bytes:
        reader, my_ack, off, n = self._wait_readable(timeout)
        data = bytes(self._shm.buf[off : off + n])
        self._set_u64(self._ack_off(reader), my_ack + 1)
        return data

    def read_view(self, timeout: Optional[float] = None) -> SlotView:
        """Zero-copy read: a lease on the slot's frame bytes. The ack
        publishes at release(), so the writer cannot recycle the slot while
        the view (or any np.frombuffer alias of it) is in use."""
        reader, my_ack, off, n = self._wait_readable(timeout)
        mv = self._shm.buf[off : off + n]
        return SlotView(
            mv, lambda: self._set_u64(self._ack_off(reader), my_ack + 1)
        )

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Writer-side: block until every written item was acked (or the
        channel closed). Stream writers call this before destroy() so the
        segment never unlinks under a reader mid-item."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._min_ack() < self._write_version:
            if self._closed:
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(5e-5)
        return True

    def close(self):
        """Mark closed: wakes blocked readers AND writers (buffered reads drain)."""
        self._set_u64(8, 1)

    def destroy(self):
        try:
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except Exception:
            pass

    def __del__(self):
        try:
            self._shm.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# Cross-node channel: same ring semantics over the workers' direct RPC servers.
#
# Design parity: reference cross-node channels are raylet-mediated mutable
# plasma objects (shared_memory_channel.py:151 + experimental_mutable_object_
# provider.h:143). Here the ring buffer lives in the WRITER's process and
# readers long-poll it over the direct worker connections the runtime already
# maintains — one RTT per item per reader, no per-item raylet involvement.
# --------------------------------------------------------------------------


class _RingState:
    """Writer-process state of one RpcChannel."""

    def __init__(self, num_readers: int, num_slots: int):
        import threading

        self.num_readers = num_readers
        self.num_slots = num_slots
        self.slots: list = [None] * num_slots
        self.write_version = 0
        self.acks = [0] * num_readers
        self.closed = False
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)


_rpc_rings: dict = {}  # channel name -> _RingState (writer process only)
_conn_cache: dict = {}  # (host, port) -> rpc.Connection (reader process)
# Guards get-or-create on the registries above: channels are touched from the
# driver thread, DAG reader/writer threads, and the RPC io thread at once — a
# lost _RingState race would strand a writer's acks, a lost conn race leaks a
# socket per edge.
_registry_lock = threading.Lock()


def _ring_pull(name: str, reader: int, index: int):
    """One non-blocking pull attempt (called from the worker's RPC handler).
    Returns {"data"}|{"closed"}|{"wait"}|{"unknown"}."""
    ring = _rpc_rings.get(name)
    if ring is None:
        return {"unknown": True}
    with ring.lock:
        if ring.write_version > index:
            data = ring.slots[index % ring.num_slots]
            if 0 <= reader < ring.num_readers:
                ring.acks[reader] = index + 1
            ring.cond.notify_all()
            return {"data": data}
        if ring.closed:
            return {"closed": True}
    return {"wait": True}


def _ring_close(name: str):
    ring = _rpc_rings.get(name)
    if ring is not None:
        with ring.lock:
            ring.closed = True
            ring.cond.notify_all()
    return True


def _ring_detach(name: str, reader: int):
    """Writer-process detach of one reader slot (multicast dead-subscriber
    unwind): its ack jumps past every write version so the ring stops
    back-pressuring on it."""
    ring = _rpc_rings.get(name)
    if ring is not None:
        with ring.lock:
            if 0 <= reader < ring.num_readers:
                ring.acks[reader] = Channel._DETACHED_ACK
                ring.cond.notify_all()
    return True


def _ring_destroy(name: str):
    """Release payload memory but keep a CLOSED tombstone: a remote reader that
    polls after destroy must see {"closed"} and unwind its pinned loop, not spin
    on {"unknown"} forever."""
    ring = _rpc_rings.get(name)
    if ring is not None:
        with ring.lock:
            ring.closed = True
            ring.slots = [None] * ring.num_slots
            ring.cond.notify_all()


class RpcChannel:
    """Channel whose ring lives in the writer's process; readers pull over the
    direct worker RPC servers. Interface-compatible with Channel (write/read,
    reader(slot), close/destroy, picklable by name)."""

    def __init__(self, capacity: int = 4 << 20, num_readers: int = 1,
                 num_slots: Optional[int] = None, owner=None, _name: Optional[str] = None,
                 _reader_slot: Optional[int] = None):
        if num_slots is None:
            from ray_tpu._private.config import CONFIG

            num_slots = CONFIG.channel_default_slots
        self._capacity = capacity  # advisory only (no fixed slot size)
        self._num_readers = num_readers
        self._num_slots = num_slots
        # owner: where the writer lives — ("actor", ActorID) resolved via the
        # GCS, or ("addr", (host, port)) for a driver-owned channel.
        self._owner = owner
        self._name = _name or f"rtpurpc_{uuid.uuid4().hex[:12]}"
        self._reader_slot = _reader_slot
        self._next = 0  # reader-side: next item index to pull
        self._conn = None

    def __reduce__(self):
        return (
            RpcChannel,
            (self._capacity, self._num_readers, self._num_slots, self._owner,
             self._name, self._reader_slot),
        )

    def reader(self, slot: int) -> "RpcChannel":
        return RpcChannel(self._capacity, self._num_readers, self._num_slots,
                          self._owner, self._name, slot)

    # -- writer (runs in the owner process) --------------------------------
    def _ring(self) -> _RingState:
        with _registry_lock:
            ring = _rpc_rings.get(self._name)
            if ring is None:
                ring = _rpc_rings[self._name] = _RingState(
                    self._num_readers, self._num_slots
                )
            return ring

    def write(self, value: Any, timeout: Optional[float] = None):
        plan = _tt.plan(value, _tensor_min_bytes())
        if plan is None:
            self.write_bytes(
                cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL),
                timeout,
            )
            return
        # Tensor fast path: the ring item is a raw tensor frame (header +
        # leaf bytes) — array data is never cloudpickled; the reader's pull
        # response carries it as one opaque buffer.
        self._write_item(bytes(plan.to_bytes()), timeout)
        _note_write(plan.total, tensor=True)

    def write_bytes(self, data: bytes, timeout: Optional[float] = None):
        self._write_item(data, timeout)
        _note_write(len(data), tensor=False)

    def _write_item(self, data: bytes, timeout: Optional[float] = None):
        ring = self._ring()
        deadline = None if timeout is None else time.monotonic() + timeout
        with ring.lock:
            while True:
                if ring.closed:
                    raise ChannelClosed()
                if ring.write_version - min(ring.acks) < ring.num_slots:
                    break
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        "channel write timed out waiting for readers"
                    )
                ring.cond.wait(0.05)
            ring.slots[ring.write_version % ring.num_slots] = data
            ring.write_version += 1
            ring.cond.notify_all()

    # -- reader (any process) ----------------------------------------------
    def _writer_conn(self):
        from ray_tpu._private import rpc
        from ray_tpu._private.worker import global_worker

        if self._conn is not None and not self._conn.closed:
            return self._conn
        w = global_worker()
        if self._owner is None:
            raise ChannelClosed()
        kind, ref = self._owner
        if kind == "addr":
            addr = tuple(ref)
        else:
            info = w.gcs_call("get_actor_info", ref)
            if info is None or info["state"] == "DEAD":
                raise ChannelClosed()
            addr = (info.get("address") or {}).get("direct_addr")
            if addr is None:
                raise ChannelClosed()
        # One socket per (process, writer address), shared by every channel
        # view into that writer — k edges into one stage must not open k conns.
        with _registry_lock:
            cached = _conn_cache.get(addr)
            if cached is not None and not cached.closed:
                self._conn = cached
                return cached
            # Connect under the lock: a losing racer must share this socket,
            # not dial its own (the connect runs on the io thread; this
            # caller thread just blocks on the handshake).
            self._conn = w.io.run(
                rpc.connect(*addr, handler=w, name=f"chan->{addr[1]}")  # raylint: disable=RL902 (connect-under-lock IS the dedup contract: a losing racer must share this socket, not dial its own)
            )
            _conn_cache[addr] = self._conn
            return self._conn

    def read(self, timeout: Optional[float] = None) -> Any:
        data = self.read_bytes(timeout)
        if _tt.is_frame(data):
            _tt.note("tensor_frames_read")
            # copy=True: `data` is an owned bytes object, but aliased arrays
            # over immutable bytes would be read-only — graph methods may
            # mutate their inputs, so materialize owning arrays.
            return _tt.decode(memoryview(data), copy=True)
        _tt.note("pickle_frames_read")
        return cloudpickle.loads(data)

    def _drop_conn(self):
        """Forget the reader's writer connection AND evict dead sockets from
        the shared cache, so the next attempt (here or on any sibling channel
        into the same writer) dials fresh instead of reusing a corpse."""
        conn, self._conn = self._conn, None
        with _registry_lock:
            for addr, c in list(_conn_cache.items()):
                if c is conn or c.closed:
                    _conn_cache.pop(addr, None)

    def read_bytes(self, timeout: Optional[float] = None) -> bytes:
        from ray_tpu._private import rpc
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        reader = self._reader_slot or 0
        deadline = None if timeout is None else time.monotonic() + timeout
        # Transient-failure window (gcs_call-style backoff + full jitter):
        # a writer process mid-restart or a dropped TCP conn must not
        # instantly become ChannelClosed — only failures that OUTLAST the
        # reconnect window (or the read deadline) declare the writer dead.
        retry_deadline: Optional[float] = None
        backoff = 0.05
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError("channel read timed out")
            # The server long-polls at most `poll` seconds, so a short read
            # timeout is honored to within one RPC, not one 25s poll.
            poll = 25.0 if remaining is None else max(0.05, min(25.0, remaining))
            try:
                conn = self._writer_conn()
                resp = w.io.run(
                    conn.call("chan_pull", self._name, reader, self._next, poll),
                    timeout=poll + 10,
                )
            except ChannelClosed:
                raise  # definitive: the GCS says the writer actor is DEAD
            except (rpc.RpcError, TimeoutError, OSError):
                import random as _random

                self._drop_conn()
                now = time.monotonic()
                if retry_deadline is None:
                    retry_deadline = now + CONFIG.channel_reconnect_s
                    if deadline is not None:
                        retry_deadline = min(retry_deadline, deadline)
                if now >= retry_deadline:
                    raise ChannelClosed()  # writer gone: the pinned loop unwinds
                pause = backoff * (0.5 + _random.random())
                pause = min(pause, max(0.0, retry_deadline - now))
                time.sleep(pause)
                backoff = min(backoff * 2.0, 1.0)
                continue
            retry_deadline = None  # healthy round-trip: arm a fresh window
            backoff = 0.05
            if "data" in resp:
                self._next += 1
                return resp["data"]
            if resp.get("closed"):
                raise ChannelClosed()
            # "wait"/"unknown": ring not created yet or nothing new yet.

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Writer-side: block until every ring item was pulled (or closed)."""
        ring = self._ring()
        deadline = None if timeout is None else time.monotonic() + timeout
        with ring.lock:
            while min(ring.acks) < ring.write_version:
                if ring.closed:
                    return False
                wait = 0.05
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                ring.cond.wait(wait)
            return True

    def lagging_readers(self):
        """Reader slots currently holding the ring back (writer process
        only; a reader-side view has no ring state and reports none)."""
        ring = _rpc_rings.get(self._name)
        if ring is None:
            return []
        with ring.lock:
            m = min(ring.acks)
            if m >= Channel._DETACHED_ACK:
                return []
            return [r for r, a in enumerate(ring.acks) if a == m]

    def detach_reader(self, reader: int):
        """Stop counting `reader` toward ring back-pressure (multicast
        dead-subscriber unwind). Writer-local rings detach directly; a
        reader-side view notifies the writer process."""
        if self._name in _rpc_rings:
            _ring_detach(self._name, reader)
            return
        try:
            conn = self._writer_conn()
            from ray_tpu._private.worker import global_worker

            global_worker().io.run(
                conn.notify("chan_detach", self._name, reader)
            )
        except Exception:
            pass  # writer already dead: nothing back-pressures anymore

    def close(self):
        # Writer-local rings close directly; otherwise tell the writer.
        if self._name in _rpc_rings:
            _ring_close(self._name)
            return
        try:
            conn = self._writer_conn()
            from ray_tpu._private.worker import global_worker

            global_worker().io.run(conn.notify("chan_close", self._name))
        except Exception:
            pass  # writer already dead: nothing to close

    def destroy(self):
        _ring_destroy(self._name)
        # The reader conn is shared per writer address (_conn_cache): just drop
        # the reference; other channels into the same writer keep using it.
        self._conn = None
