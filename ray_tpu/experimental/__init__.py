"""Experimental transports: compiled-graph channels + device-object plane.

Round 11 adds the tensor-native layer (docs/device_channels.md): `Channel` /
`RpcChannel` carry array payloads as raw-buffer frames, and `DeviceChannel`
streams device arrays in pipelined chunks (local handoff / shm ring /
chunked RPC)."""

from ray_tpu.experimental.channel import (  # noqa: F401
    Channel,
    ChannelClosed,
    RpcChannel,
    SlotView,
)
from ray_tpu.experimental.device_channel import DeviceChannel  # noqa: F401
from ray_tpu.experimental.tensor_transport import (  # noqa: F401
    reset_transport_stats,
    transport_stats,
)
