"""Device-resident objects: tensor payloads that stay in accelerator memory.

Design parity: reference "Ray Direct Transport" (RDT) —
`python/ray/experimental/gpu_object_manager/` + `@ray.remote(tensor_transport=...)`:
ObjectRefs whose tensor payload never leaves device memory on the producing actor;
consumers on the same actor use it with zero transfer, remote consumers fetch it
through a transport (NCCL/NIXL there). TPU-first shape: jax Arrays live in the
producing actor's HBM keyed by a small DeviceObjectRef descriptor that travels
through the ordinary object plane; same-actor resolution is a dict lookup (no
transfer), cross-process resolution is one host round-trip (device_get -> numpy ->
object plane). On TPU pods, tensors that must move BETWEEN chips belong inside
jitted SPMD programs where XLA schedules ICI collectives — this API is for keeping
large tensors pinned to an actor across calls (KV caches, optimizer state,
sampled rollouts) without paying host serialization per call.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu._private.ids import ActorID


@dataclass(frozen=True)
class DeviceObjectRef:
    """A handle to a tensor living in a specific actor's device memory.

    Round 3: descriptors are first-class refcounted references — `ref` is an
    ordinary ObjectRef owned by the pinning actor, so descriptors ride the
    sequenced borrow protocol like any ref, and the HBM pin releases when the
    LAST descriptor anywhere goes out of scope (RDT parity: reference
    `gpu_object_manager.py` frees device objects via the reference counter,
    not actor death)."""

    actor_id: ActorID
    key: str
    shape: tuple
    dtype: str
    ref: Optional[Any] = field(default=None, compare=False)

    def __repr__(self):
        return (
            f"DeviceObjectRef({self.key[:8]}@{self.actor_id.hex()[:8]}, "
            f"{self.dtype}{list(self.shape)})"
        )


class _ActorDeviceStore:
    """Per-process store of device arrays (the gpu_object_store.py role)."""

    def __init__(self):
        self._objects: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def put(self, key: str, value):
        with self._lock:
            self._objects[key] = value

    def get(self, key: str):
        with self._lock:
            if key not in self._objects:
                raise ValueError(
                    f"device object {key[:8]}… is not pinned here: it was freed, "
                    f"its owner restarted, or the descriptor is stale"
                )
            return self._objects[key]

    def pop(self, key: str):
        with self._lock:
            return self._objects.pop(key, None)

    def keys(self):
        with self._lock:
            return list(self._objects)


_store = _ActorDeviceStore()


def _current_actor_id() -> ActorID:
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    if w.actor_id is None:
        raise RuntimeError(
            "device objects live in actor processes; put() must run inside an "
            "actor method (reference: RDT objects are actor-owned)"
        )
    return w.actor_id


def put(value) -> DeviceObjectRef:
    """Pin a (jax) array in THIS actor's device memory; return its descriptor.

    The descriptor is tiny and travels through the normal object plane. Its
    embedded ObjectRef is owned by this actor: when every holder's reference
    dies (tracked by the sequenced borrow protocol), the owner's free hook
    evicts the HBM pin automatically — no explicit free() needed."""
    import jax.numpy as jnp

    from ray_tpu._private import serialization
    from ray_tpu._private.worker import global_worker

    actor_id = _current_actor_id()  # validate context BEFORE pinning anything
    w = global_worker()
    # Unconditional device placement: a numpy input must land in HBM, or every
    # later use pays host->device per call; no-op for arrays already on device.
    arr = jnp.asarray(value)
    key = uuid.uuid4().hex
    _store.put(key, arr)
    # Back the descriptor with an owned, refcounted id (the record resolves to
    # a sentinel so a stray ray.get() on the raw ref returns something legible
    # instead of hanging); the free hook evicts the pin on last release.
    ref = w.put_inline_owned(
        serialization.dumps({"device_object": key, "actor": actor_id.hex()}),
        free_hook=lambda: _store.pop(key),
    )
    return DeviceObjectRef(
        actor_id=actor_id,
        key=key,
        shape=tuple(arr.shape),
        dtype=str(arr.dtype),
        ref=ref,
    )


def _run_on_owner(ref: DeviceObjectRef, local_fn, remote_fn):
    """Local dict op on the owner; one remote __rtpu_apply__ hop elsewhere."""
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    if w.actor_id is not None and w.actor_id == ref.actor_id:
        return local_fn()
    import ray_tpu
    from ray_tpu.actor import ActorHandle, ActorMethod

    handle = ActorHandle(ref.actor_id, [], "DeviceObjectOwner")
    return ray_tpu.get(
        ActorMethod(handle, "__rtpu_apply__").remote(remote_fn, ref.key)
    )


def get(ref: DeviceObjectRef):
    """Resolve a descriptor to its array.

    Same actor: the device array itself, zero transfer. Elsewhere: one fetch
    through the owning actor (device -> host numpy -> object plane) — the
    explicit-transport fallback, like RDT's non-collective path."""
    return _run_on_owner(ref, lambda: _store.get(ref.key), _fetch_host)


def free(ref: DeviceObjectRef) -> bool:
    """EARLY-release the pinned array on its owner. Usually unnecessary:
    descriptors are refcounted and the pin evicts when the last one dies —
    free() is for reclaiming HBM while descriptors still circulate (their
    get() then raises)."""
    return _run_on_owner(ref, lambda: _store.pop(ref.key) is not None, _free_local)


def transfer(ref: DeviceObjectRef, dst_actor,
             free_src: bool = False) -> DeviceObjectRef:
    """COPY a device object into another actor's memory, peer-to-peer.

    The destination actor pulls the tensor FROM the owner directly (actor-to-
    actor over the data plane — the caller only relays the tiny descriptor,
    never the payload; reference:
    `experimental/collective/tensor_transport_manager.py` p2p transports).
    Returns a new descriptor owned by `dst_actor`. The SOURCE pin stays alive
    until its descriptors die (or pass ``free_src=True`` for move semantics —
    mind other holders: their get() will then raise)."""
    import ray_tpu
    from ray_tpu.actor import ActorMethod

    out = ray_tpu.get(
        ActorMethod(dst_actor, "__rtpu_apply__").remote(_pull_and_pin, ref)
    )
    if free_src:
        free(ref)
    return out


async def _pull_and_pin(_instance, ref: DeviceObjectRef) -> DeviceObjectRef:
    """Runs on the DESTINATION actor: fetch from the owner, pin locally.
    Async so an async-actor destination's event loop never stalls behind the
    (possibly multi-MB) pull; sync actors run the coroutine on their executor
    thread via __rtpu_apply__."""
    import asyncio

    value = await asyncio.to_thread(get, ref)  # owner-direct fetch
    return put(value)


async def _fetch_host(_instance, key: str):
    """Runs on the owning actor: device -> host for the object plane. Async so
    an async-actor owner's event loop never stalls behind the D2H copy of a
    large tensor (KV prefixes are tens of MB) — the copy runs on a thread;
    sync-actor owners just run the coroutine on their executor thread."""
    import asyncio

    import numpy as np

    arr = _store.get(key)
    return await asyncio.to_thread(np.asarray, arr)


def _free_local(_instance, key: str) -> bool:
    return _store.pop(key) is not None


def stored_keys() -> list:
    """Keys pinned in THIS process (introspection/testing)."""
    return _store.keys()
