"""Device-resident objects: tensor payloads that stay in accelerator memory.

Design parity: reference "Ray Direct Transport" (RDT) —
`python/ray/experimental/gpu_object_manager/` + `@ray.remote(tensor_transport=...)`:
ObjectRefs whose tensor payload never leaves device memory on the producing actor;
consumers on the same actor use it with zero transfer, remote consumers fetch it
through a transport (NCCL/NIXL there). TPU-first shape: jax Arrays live in the
producing actor's HBM keyed by a small DeviceObjectRef descriptor that travels
through the ordinary object plane; same-actor resolution is a dict lookup (no
transfer), cross-process resolution is one host round-trip (device_get -> numpy ->
object plane). On TPU pods, tensors that must move BETWEEN chips belong inside
jitted SPMD programs where XLA schedules ICI collectives — this API is for keeping
large tensors pinned to an actor across calls (KV caches, optimizer state,
sampled rollouts) without paying host serialization per call.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu._private.ids import ActorID


@dataclass(frozen=True)
class DeviceObjectRef:
    """A handle to a tensor living in a specific actor's device memory.

    Round 3: descriptors are first-class refcounted references — `ref` is an
    ordinary ObjectRef owned by the pinning actor, so descriptors ride the
    sequenced borrow protocol like any ref, and the HBM pin releases when the
    LAST descriptor anywhere goes out of scope (RDT parity: reference
    `gpu_object_manager.py` frees device objects via the reference counter,
    not actor death)."""

    actor_id: ActorID
    key: str
    shape: tuple
    dtype: str
    ref: Optional[Any] = field(default=None, compare=False)

    def __repr__(self):
        return (
            f"DeviceObjectRef({self.key[:8]}@{self.actor_id.hex()[:8]}, "
            f"{self.dtype}{list(self.shape)})"
        )


class _ActorDeviceStore:
    """Per-process store of device arrays (the gpu_object_store.py role)."""

    def __init__(self):
        self._objects: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def put(self, key: str, value):
        with self._lock:
            self._objects[key] = value

    def get(self, key: str):
        with self._lock:
            if key not in self._objects:
                raise ValueError(
                    f"device object {key[:8]}… is not pinned here: it was freed, "
                    f"its owner restarted, or the descriptor is stale"
                )
            return self._objects[key]

    def pop(self, key: str):
        with self._lock:
            return self._objects.pop(key, None)

    def keys(self):
        with self._lock:
            return list(self._objects)


_store = _ActorDeviceStore()


def _current_actor_id() -> ActorID:
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    if w.actor_id is None:
        raise RuntimeError(
            "device objects live in actor processes; put() must run inside an "
            "actor method (reference: RDT objects are actor-owned)"
        )
    return w.actor_id


def put(value) -> DeviceObjectRef:
    """Pin a (jax) array in THIS actor's device memory; return its descriptor.

    The descriptor is tiny and travels through the normal object plane. Its
    embedded ObjectRef is owned by this actor: when every holder's reference
    dies (tracked by the sequenced borrow protocol), the owner's free hook
    evicts the HBM pin automatically — no explicit free() needed."""
    import jax.numpy as jnp

    from ray_tpu._private import serialization
    from ray_tpu._private.worker import global_worker

    actor_id = _current_actor_id()  # validate context BEFORE pinning anything
    w = global_worker()
    # Unconditional device placement: a numpy input must land in HBM, or every
    # later use pays host->device per call; no-op for arrays already on device.
    arr = jnp.asarray(value)
    key = uuid.uuid4().hex
    _store.put(key, arr)
    # Back the descriptor with an owned, refcounted id (the record resolves to
    # a sentinel so a stray ray.get() on the raw ref returns something legible
    # instead of hanging); the free hook evicts the pin on last release.
    ref = w.put_inline_owned(
        serialization.dumps({"device_object": key, "actor": actor_id.hex()}),
        free_hook=lambda: _store.pop(key),
    )
    return DeviceObjectRef(
        actor_id=actor_id,
        key=key,
        shape=tuple(arr.shape),
        dtype=str(arr.dtype),
        ref=ref,
    )


def _run_on_owner(ref: DeviceObjectRef, local_fn, remote_fn):
    """Local dict op on the owner; one remote __rtpu_apply__ hop elsewhere."""
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    if w.actor_id is not None and w.actor_id == ref.actor_id:
        return local_fn()
    import ray_tpu
    from ray_tpu.actor import ActorHandle, ActorMethod

    handle = ActorHandle(ref.actor_id, [], "DeviceObjectOwner")
    return ray_tpu.get(
        ActorMethod(handle, "__rtpu_apply__").remote(remote_fn, ref.key)
    )


def get(ref: DeviceObjectRef, *, to_device: bool = False,
        on_chunk=None, sharding=None, _legacy: bool = False):
    """Resolve a descriptor to its array.

    Same actor: the device array itself, zero transfer. Elsewhere the payload
    streams over a DeviceChannel (round 11, docs/device_channels.md): the
    owner writes chunked raw frames — a shm ring on the same node, RPC frames
    across nodes — and this side assembles as they arrive, so D2H, wire, and
    assembly pipeline instead of one blocking full-tensor hop through the
    object plane. `to_device=True` stages each chunk onto the local device as
    it lands (`jax.device_put` per chunk + one device concatenate), and
    `on_chunk(leaf_idx, elt_offset, typed_chunk)` tees arriving chunks to the
    caller. `sharding` (implies to_device) is the consumer's target mesh
    layout: a mesh-sharded payload whose shard bounds match stages each
    arriving shard straight onto its own device — the sharded PD handoff
    path (docs/serving_tp.md).

    Payloads below `devobj_stream_min_bytes` take the one-hop object-plane
    blob instead: a stream pays a control round-trip plus ring setup, which
    only amortizes on multi-MB tensors (BENCH_PD.json). `_legacy=True`
    forces that path explicitly."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    if sharding is not None:
        to_device = True
    if w.actor_id is not None and w.actor_id == ref.actor_id:
        value = _store.get(ref.key)
        if sharding is not None:
            import jax

            # Same-actor, different layout: one explicit placement (XLA
            # moves the bytes over ICI; no host staging).
            value = jax.device_put(value, sharding)
        return value
    # on_chunk only has meaning on the stream, so a tee request overrides
    # the size gate.
    if (not _legacy
            and (on_chunk is not None
                 or _descriptor_nbytes(ref) >= CONFIG.devobj_stream_min_bytes)):
        try:
            return _stream_fetch(ref, to_device=to_device, on_chunk=on_chunk,
                                 sharding=sharding)
        except _StreamUnsupported:
            pass  # owner predates streams or this process has no data plane
    value = _run_on_owner(ref, lambda: _store.get(ref.key), _fetch_host)
    if to_device:
        import jax

        value = (jax.device_put(value, sharding) if sharding is not None
                 else jax.device_put(value))
    return value


def free(ref: DeviceObjectRef) -> bool:
    """EARLY-release the pinned array on its owner. Usually unnecessary:
    descriptors are refcounted and the pin evicts when the last one dies —
    free() is for reclaiming HBM while descriptors still circulate (their
    get() then raises)."""
    return _run_on_owner(ref, lambda: _store.pop(ref.key) is not None, _free_local)


def transfer(ref: DeviceObjectRef, dst_actor,
             free_src: bool = False) -> DeviceObjectRef:
    """COPY a device object into another actor's memory, peer-to-peer.

    The destination actor pulls the tensor FROM the owner directly (actor-to-
    actor over the data plane — the caller only relays the tiny descriptor,
    never the payload; reference:
    `experimental/collective/tensor_transport_manager.py` p2p transports).
    Returns a new descriptor owned by `dst_actor`. The SOURCE pin stays alive
    until its descriptors die (or pass ``free_src=True`` for move semantics —
    mind other holders: their get() will then raise)."""
    import ray_tpu
    from ray_tpu.actor import ActorMethod

    out = ray_tpu.get(
        ActorMethod(dst_actor, "__rtpu_apply__").remote(_pull_and_pin, ref)
    )
    if free_src:
        free(ref)
    return out


async def _pull_and_pin(_instance, ref: DeviceObjectRef) -> DeviceObjectRef:
    """Runs on the DESTINATION actor: fetch from the owner, pin locally.
    Async so an async-actor destination's event loop never stalls behind the
    (possibly multi-MB) pull; sync actors run the coroutine on their executor
    thread via __rtpu_apply__."""
    import asyncio

    value = await asyncio.to_thread(get, ref)  # owner-direct fetch
    return put(value)


class _StreamUnsupported(Exception):
    """Streamed fetch cannot run here (no data plane / pre-stream owner)."""


def _descriptor_nbytes(ref: DeviceObjectRef) -> int:
    """Payload size from the descriptor alone (no owner round-trip). Unknown
    dtypes (extension dtypes not registered here) count as large: streaming
    is the safe default for anything that might be big."""
    import numpy as np

    try:
        itemsize = np.dtype(ref.dtype).itemsize
    except TypeError:
        return 1 << 62
    n = itemsize
    for d in ref.shape:
        n *= int(d)
    return n


# -- in-flight host-snapshot dedupe (round 11 satellite) ---------------------
# Concurrent consumers pulling the SAME key used to materialize the full
# tensor on the owner's executor once PER CONSUMER. One in-flight snapshot
# per key is shared by every fetch that arrives while it materializes; the
# entry clears on completion so memory is bounded by live requests, not a
# cache.
_snapshot_lock = threading.Lock()
_inflight_snapshots: Dict[str, list] = {}  # key -> [Event, value, exc]
_snapshot_materializations = 0  # introspection/testing
_TEST_SNAPSHOT_DELAY_S = 0.0  # test hook: widen the dedupe window


def _host_snapshot(key: str):
    """Host numpy view of a pinned device array; concurrent callers share one
    D2H materialization per key."""
    import numpy as np

    global _snapshot_materializations
    with _snapshot_lock:
        entry = _inflight_snapshots.get(key)
        if entry is None:
            entry = [threading.Event(), None, None]
            _inflight_snapshots[key] = entry
            owner = True
            _snapshot_materializations += 1
        else:
            owner = False
    if not owner:
        entry[0].wait()
        if entry[2] is not None:
            raise entry[2]
        return entry[1]
    try:
        arr = _store.get(key)
        if _TEST_SNAPSHOT_DELAY_S:
            time.sleep(_TEST_SNAPSHOT_DELAY_S)
        entry[1] = np.asarray(arr)
        return entry[1]
    except BaseException as e:  # noqa: BLE001 - waiters must observe failure
        entry[2] = e
        raise
    finally:
        with _snapshot_lock:
            _inflight_snapshots.pop(key, None)
        entry[0].set()


async def _fetch_host(_instance, key: str):
    """Runs on the owning actor: device -> host for the object plane. Async so
    an async-actor owner's event loop never stalls behind the D2H copy of a
    large tensor (KV prefixes are tens of MB) — the copy runs on a thread;
    sync-actor owners just run the coroutine on their executor thread.
    Concurrent fetches of one key share a single in-flight snapshot."""
    import asyncio

    return await asyncio.to_thread(_host_snapshot, key)


def _free_local(_instance, key: str) -> bool:
    return _store.pop(key) is not None


# -- chunked streaming (round 11 tentpole) -----------------------------------

_active_streams = 0  # writer-side pumps still holding a snapshot/segment
_streams_lock = threading.Lock()


def active_streams() -> int:
    """Writer-side streams still live in THIS process (introspection: a
    drained/aborted stream must release its snapshot pin and shm segment)."""
    with _streams_lock:
        return _active_streams


def _register_stream_ledger():
    """Join the device-memory ledger (docs/observability.md "compute
    plane"): a live stream pins a host snapshot + shm segment; the ledger
    surfaces the count so an OOM snapshot can implicate a stuck pump even
    though the pinned bytes are host-side (reported as count, not bytes)."""
    from ray_tpu.util import xprof

    xprof.register_memory_owner(
        "device_channel_streams",
        lambda: {"bytes": 0, "streams": active_streams()},
    )


_register_stream_ledger()


_devobj_metrics: dict = {}
_devobj_metrics_lock = threading.Lock()


def _metric(name: str):
    with _devobj_metrics_lock:
        m = _devobj_metrics.get(name)
        if m is None:
            from ray_tpu.util import metrics

            if name == "devobj_transfer_bytes":
                m = metrics.Counter(
                    "devobj_transfer_bytes",
                    "tensor bytes moved by device-object fetches/transfers",
                )
            else:
                m = metrics.Histogram(
                    "devobj_transfer_seconds",
                    "wall time of device-object fetches/transfers",
                    boundaries=[0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10],
                )
            _devobj_metrics[name] = m
        return m


def _note_transfer(nbytes: int, seconds: float):
    try:
        _metric("devobj_transfer_bytes").inc(nbytes)
        _metric("devobj_transfer_seconds").observe(seconds)
    except Exception:
        pass  # observability must never break the transfer


def _open_stream(_instance, key: str, reader_node, chunk_bytes):
    """Runs on the OWNING actor: mint a DeviceChannel toward `reader_node`
    and pump the pinned array through it on a background thread. Returns the
    (picklable) channel for the reader end. The pump holds its own reference
    to the array, so a concurrent free() cannot unpin bytes mid-stream, and
    destroys the ring once the reader drained it (or closed early)."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.experimental.channel import ChannelClosed
    from ray_tpu.experimental.device_channel import DeviceChannel

    global _active_streams
    arr = _store.get(key)  # raises for freed/stale keys BEFORE minting a ring
    w = global_worker()
    same_node = reader_node is not None and reader_node == w.node_id
    ch = DeviceChannel.create(
        same_node=same_node, chunk_bytes=chunk_bytes,
        owner=None if same_node else ("actor", w.actor_id),
    )
    with _streams_lock:
        _active_streams += 1
    from ray_tpu.devtools import leaksan as _leaksan

    stream_token = f"devobj-stream:{key[:8]}@{id(ch):x}"
    _leaksan.track("devobj_stream", token=stream_token)

    def pump():
        global _active_streams
        try:
            ch.send(arr, timeout=120.0)
            ch.drain(timeout=120.0)
        except (ChannelClosed, TimeoutError):
            pass  # reader closed early or died: unwind, release the pin
        except Exception:
            pass  # never let a pump thread take the actor down
        finally:
            try:
                ch.destroy()
            finally:
                with _streams_lock:
                    _active_streams -= 1
                _leaksan.untrack("devobj_stream", token=stream_token)

    threading.Thread(target=pump, name="devobj-stream", daemon=True).start()
    return ch


def _stream_fetch(ref: DeviceObjectRef, *, to_device: bool, on_chunk=None,
                  sharding=None):
    """Reader side of the chunked pull; raises _StreamUnsupported when the
    topology cannot stream (caller falls back to the object-plane blob)."""
    import ray_tpu
    from ray_tpu._private.worker import global_worker
    from ray_tpu._private.config import CONFIG
    from ray_tpu.actor import ActorHandle, ActorMethod

    w = global_worker()
    if CONFIG.llm_channel_chunk_bytes <= 0:
        raise _StreamUnsupported()
    handle = ActorHandle(ref.actor_id, [], "DeviceObjectOwner")
    t0 = time.monotonic()
    ch = ray_tpu.get(
        ActorMethod(handle, "__rtpu_apply__").remote(
            _open_stream, ref.key, w.node_id, CONFIG.llm_channel_chunk_bytes
        )
    )
    try:
        if to_device:
            value = ch.recv_device(timeout=120.0, sharding=sharding)
            nbytes = sum(
                int(x.size) * x.dtype.itemsize
                for x in _leaves_of(value)
            )
        else:
            value = ch.recv(on_chunk=on_chunk, timeout=120.0)
            nbytes = sum(x.nbytes for x in _leaves_of(value))
    except BaseException:
        # Unwind the writer: close wakes its blocked send, so the pinned
        # snapshot and the ring release instead of leaking.
        try:
            ch.close()
        except Exception:
            pass
        raise
    _note_transfer(nbytes, time.monotonic() - t0)
    return value


def _leaves_of(value):
    import numpy as np

    if isinstance(value, np.ndarray):
        return [value]
    import sys as _sys

    jax = _sys.modules.get("jax")
    if jax is not None and isinstance(value, jax.Array):
        return [value]
    if isinstance(value, dict):
        return [x for v in value.values() for x in _leaves_of(v)]
    if isinstance(value, (list, tuple)):
        return [x for v in value for x in _leaves_of(v)]
    return []


def stored_keys() -> list:
    """Keys pinned in THIS process (introspection/testing)."""
    return _store.keys()
