"""DeviceChannel: tensor-native streaming transport for device arrays.

Design parity: the reference gives compiled graphs dedicated tensor
transports next to the shared-memory channel (NCCL channels in
`python/ray/experimental/channel/`, NIXL for PD KV in
`prefill_decode_disagg.py`). TPU-first shape (docs/device_channels.md):

  transport decision table
  ------------------------
  writer/reader same process   local handoff: `jax.device_put` with the
                               target sharding (XLA schedules the ICI
                               collective transfer); zero host staging.
  same node, different process shm chunk ring: device->host slices memcpy'd
                               into `Channel` slots; the reader maps each
                               slot zero-copy (`read_view`) and assembles or
                               device_puts straight off shared memory.
  cross node                   chunked RPC frames over the writer-owned
                               `RpcChannel` ring (the NIXL-role fallback).

Either way the payload moves as raw chunk frames behind one small pickled
header — never through cloudpickle — and the ring depth
(`devobj_stream_slots`) is the pipeline: the writer's next D2H slice
overlaps the reader's copy/H2D of the previous chunk, instead of one
blocking `device_get` of tens of MB (`llm_channel_chunk_bytes` sets the
granularity).
"""

from __future__ import annotations

import pickle
import struct
import sys
import threading
import time
import uuid
from typing import Any, Callable, List, Optional

import numpy as np

from ray_tpu.experimental import tensor_transport as _tt
from ray_tpu.experimental.channel import Channel, ChannelClosed, RpcChannel

STREAM_MAGIC = b"RTS1"
_U32 = struct.Struct("<I")

# Local-handoff rings (same-process writer/reader), keyed by channel name.
_local_rings: dict = {}
_local_lock = threading.Lock()


class _LocalRing:
    def __init__(self):
        self.items: list = []
        self.closed = False
        self.cond = threading.Condition()


def _leaf_meta(leaf) -> tuple:
    """(shape, np.dtype, size_elems) of an array leaf (jax or numpy)."""
    dtype = np.dtype(leaf.dtype)
    size = 1
    for d in leaf.shape:
        size *= int(d)
    return tuple(leaf.shape), dtype, size


def _chunk_elems(dtype: np.dtype, chunk_bytes: int) -> int:
    return max(1, chunk_bytes // max(1, dtype.itemsize))


def _host_resident(leaf) -> bool:
    """True when a jax Array's bytes are already host-addressable (CPU
    backend): streaming then slices ONE host view instead of dispatching a
    device slice + transfer per chunk."""
    try:
        return all(d.platform == "cpu" for d in leaf.devices())
    except Exception:
        return False


class DeviceChannel:
    """One-writer one-reader stream of array trees over a chunked transport.

    `transport=None` is the local (same-process) mode; otherwise a `Channel`
    (same-node shm) or `RpcChannel` (cross-node) carries the frames. The
    object pickles by transport identity, so a writer can mint a channel and
    ship the reader end through any control-plane message."""

    def __init__(self, transport=None, chunk_bytes: Optional[int] = None,
                 name: Optional[str] = None):
        if chunk_bytes is None:
            from ray_tpu._private.config import CONFIG

            chunk_bytes = CONFIG.llm_channel_chunk_bytes
        self._transport = transport
        self._chunk = int(chunk_bytes)
        self._name = name or f"rtpudev_{uuid.uuid4().hex[:12]}"
        if transport is None:
            with _local_lock:
                _local_rings.setdefault(self._name, _LocalRing())

    @classmethod
    def create(cls, *, same_node: bool = True, local: bool = False,
               chunk_bytes: Optional[int] = None,
               num_slots: Optional[int] = None,
               owner=None) -> "DeviceChannel":
        from ray_tpu._private.config import CONFIG

        chunk = chunk_bytes or CONFIG.llm_channel_chunk_bytes
        if local:
            return cls(None, chunk)
        slots = num_slots or CONFIG.devobj_stream_slots
        # Headroom past the chunk size: the header frame (pickled skeleton +
        # leaf descriptors) rides the same ring.
        capacity = int(chunk) + (64 << 10)
        if same_node:
            transport = Channel(capacity, num_readers=1, num_slots=slots)
        else:
            transport = RpcChannel(capacity, num_readers=1, num_slots=slots,
                                   owner=owner)
        return cls(transport, chunk)

    def __reduce__(self):
        return (DeviceChannel, (self._transport, self._chunk, self._name))

    # -- local mode --------------------------------------------------------
    def _local(self) -> _LocalRing:
        with _local_lock:
            ring = _local_rings.get(self._name)
        if ring is None:
            raise RuntimeError(
                "local DeviceChannel crossed a process boundary: same-process "
                "handoff requires writer and reader in one process — use "
                "create(same_node=...) for cross-process streams"
            )
        return ring

    # -- writer ------------------------------------------------------------
    def send(self, value: Any, *, sharding=None,
             timeout: Optional[float] = None):
        """Stream `value`'s array leaves to the reader.

        Local mode: the arrays are handed over by reference — with a
        `sharding`, via `jax.device_put(x, sharding)` so XLA moves the bytes
        over ICI to the target devices; no host staging.

        Transport mode: one header frame, then each leaf's bytes as chunk
        frames. jax leaves are sliced ON DEVICE and fetched chunk-at-a-time,
        so the D2H leg pipelines with the wire leg through the ring."""
        if self._transport is None:
            item = value
            if sharding is not None:
                import jax

                item = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, sharding), value
                )
            ring = self._local()
            with ring.cond:
                if ring.closed:
                    raise ChannelClosed()
                ring.items.append(item)
                ring.cond.notify_all()
            return
        skeleton_bytes, leaves = _tt.split(value, 0)
        descs = [_leaf_meta(leaf) for leaf in leaves]
        meta = pickle.dumps(
            (skeleton_bytes, descs, self._chunk),
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        self._transport.write_bytes(
            STREAM_MAGIC + _U32.pack(len(meta)) + meta, timeout
        )
        rpc = isinstance(self._transport, RpcChannel)
        jax = sys.modules.get("jax")
        for leaf, (_shape, dtype, size) in zip(leaves, descs):
            ce = _chunk_elems(dtype, self._chunk)
            if (jax is not None and isinstance(leaf, jax.Array)
                    and not _host_resident(leaf)):
                flat = jax.numpy.reshape(leaf, (-1,))
                for a in range(0, size, ce):
                    # Chunked D2H: one slice transfer per frame; the ring
                    # back-pressures, so at most `num_slots` chunks of host
                    # staging exist at once.
                    chunk = np.asarray(flat[a : min(size, a + ce)])  # raylint: disable=RL603 (the chunked D2H leg itself — one bounded slice per frame IS the point)
                    self._transport.write_bytes(
                        bytes(chunk.view(np.uint8).data) if rpc
                        else _tt.as_flat_bytes(chunk).data,
                        timeout,
                    )
            else:
                if not isinstance(leaf, np.ndarray):
                    # CPU-backed jax array: ONE host view (zero-copy on the
                    # CPU backend), then plain buffer slices — per-chunk
                    # device slicing would pay a jax dispatch per frame for
                    # bytes that are already host-addressable.
                    leaf = np.asarray(leaf)
                flatb = _tt.as_flat_bytes(np.ascontiguousarray(leaf))
                isz = dtype.itemsize
                for a in range(0, size, ce):
                    b = min(size, a + ce)
                    mv = flatb[a * isz : b * isz].data
                    self._transport.write_bytes(bytes(mv) if rpc else mv,
                                                timeout)
        # One logical tensor frame per stream in the fast-path accounting
        # (the per-chunk byte counts land via the transport's write_bytes).
        _tt.note("tensor_frames_written")
        from ray_tpu.experimental.channel import _metric

        try:
            _metric("chan_tensor_fastpath_total").inc()
        except Exception:
            pass  # observability must never break the stream

    # -- reader ------------------------------------------------------------
    def recv(self, *, on_chunk: Optional[Callable] = None,
             assemble: bool = True, timeout: Optional[float] = None) -> Any:
        """Read one streamed value.

        Default: assemble each leaf into a host numpy array and return the
        joined tree. `on_chunk(leaf_idx, elt_offset, typed_chunk)` is invoked
        per chunk AS FRAMES ARRIVE — over shm the chunk is a ZERO-COPY view
        of the ring slot, valid only for the duration of the callback (copy
        or device_put before returning). With assemble=False only the
        callback sees the payload and array leaves join as None (pure
        streaming consumers: PD attach staging, progress tees)."""
        if self._transport is None:
            ring = self._local()
            deadline = None if timeout is None else time.monotonic() + timeout
            with ring.cond:
                while not ring.items:
                    if ring.closed:
                        raise ChannelClosed()
                    wait = 0.1
                    if deadline is not None:
                        wait = min(wait, deadline - time.monotonic())
                        if wait <= 0:
                            raise TimeoutError("device channel recv timed out")
                    ring.cond.wait(wait)
                return ring.items.pop(0)
        header = self._transport.read_bytes(timeout)
        if bytes(header[:4]) != STREAM_MAGIC:
            raise ValueError(
                "device channel stream out of sync: expected a header frame"
            )
        (meta_len,) = _U32.unpack_from(header, 4)
        skeleton_bytes, descs, chunk_bytes = pickle.loads(
            memoryview(header)[8 : 8 + meta_len]
        )
        shm = isinstance(self._transport, Channel)
        leaves: List[Optional[np.ndarray]] = []
        for li, (shape, dtype, size) in enumerate(descs):
            out = np.empty(size, dtype) if assemble else None
            ce = _chunk_elems(dtype, chunk_bytes)
            for a in range(0, size, ce):
                b = min(size, a + ce)
                if shm:
                    view = self._transport.read_view(timeout)
                    try:
                        typed = np.frombuffer(view.mv, dtype=dtype)
                        if assemble:
                            out[a:b] = typed
                        if on_chunk is not None:
                            on_chunk(li, a, typed)
                    finally:
                        del typed  # drop the slot alias before the ack
                        view.release()
                else:
                    data = self._transport.read_bytes(timeout)
                    typed = np.frombuffer(data, dtype=dtype)
                    if assemble:
                        out[a:b] = typed
                    if on_chunk is not None:
                        on_chunk(li, a, typed)
            leaves.append(out.reshape(shape) if assemble else None)
        return _tt.join(skeleton_bytes, leaves)

    def recv_device(self, timeout: Optional[float] = None) -> Any:
        """Read one streamed value with per-chunk DEVICE staging: each chunk
        is `jax.device_put` as it arrives (H2D overlaps the wire/D2H legs),
        then leaves assemble on device with one concatenate+reshape — the
        host never holds a full copy of any leaf.

        Dtypes follow jax's x64 rules on the receiving process (int64/float64
        chunks downcast unless jax_enable_x64 is on); use recv() when the
        consumer needs bitwise host fidelity for wide dtypes."""
        import jax
        import jax.numpy as jnp

        if self._transport is None:
            return self.recv(timeout=timeout)
        header = self._transport.read_bytes(timeout)
        if bytes(header[:4]) != STREAM_MAGIC:
            raise ValueError(
                "device channel stream out of sync: expected a header frame"
            )
        (meta_len,) = _U32.unpack_from(header, 4)
        skeleton_bytes, descs, chunk_bytes = pickle.loads(
            memoryview(header)[8 : 8 + meta_len]
        )
        shm = isinstance(self._transport, Channel)
        leaves = []
        for shape, dtype, size in descs:
            ce = _chunk_elems(dtype, chunk_bytes)
            chunks = []
            for a in range(0, size, ce):
                if shm:
                    view = self._transport.read_view(timeout)
                    try:
                        # Owned host copy before device_put: the CPU backend
                        # may alias host memory, and the slot recycles at
                        # release.
                        host = np.frombuffer(view.mv, dtype=dtype).copy()
                    finally:
                        view.release()
                else:
                    host = np.frombuffer(
                        self._transport.read_bytes(timeout), dtype=dtype
                    )
                chunks.append(jax.device_put(host))
            if not chunks:
                flat = jnp.zeros((0,), dtype)
            elif len(chunks) == 1:
                flat = chunks[0]
            else:
                flat = jnp.concatenate(chunks)
            leaves.append(jnp.reshape(flat, shape))
        return _tt.join(skeleton_bytes, leaves)

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        if self._transport is None:
            ring = self._local()
            with ring.cond:
                ring.closed = True
                ring.cond.notify_all()
            return
        self._transport.close()

    def drain(self, timeout: Optional[float] = None) -> bool:
        if self._transport is None:
            return True
        return self._transport.drain(timeout)

    def destroy(self):
        if self._transport is None:
            with _local_lock:
                _local_rings.pop(self._name, None)
            return
        self._transport.destroy()
