"""DeviceChannel: tensor-native streaming transport for device arrays.

Design parity: the reference gives compiled graphs dedicated tensor
transports next to the shared-memory channel (NCCL channels in
`python/ray/experimental/channel/`, NIXL for PD KV in
`prefill_decode_disagg.py`). TPU-first shape (docs/device_channels.md):

  transport decision table
  ------------------------
  writer/reader same process   local handoff: `jax.device_put` with the
                               target sharding (XLA schedules the ICI
                               collective transfer); zero host staging.
  same node, different process shm chunk ring: device->host slices memcpy'd
                               into `Channel` slots; the reader maps each
                               slot zero-copy (`read_view`) and assembles or
                               device_puts straight off shared memory.
  cross node                   chunked RPC frames over the writer-owned
                               `RpcChannel` ring (the NIXL-role fallback).

Either way the payload moves as raw chunk frames behind one small pickled
header — never through cloudpickle — and the ring depth
(`devobj_stream_slots`) is the pipeline: the writer's next D2H slice
overlaps the reader's copy/H2D of the previous chunk, instead of one
blocking `device_get` of tens of MB (`llm_channel_chunk_bytes` sets the
granularity).
"""

from __future__ import annotations

import pickle
import struct
import sys
import threading
import time
import uuid
from typing import Any, Callable, List, Optional

import numpy as np

from ray_tpu.experimental import tensor_transport as _tt
from ray_tpu.experimental.channel import Channel, ChannelClosed, RpcChannel

STREAM_MAGIC = b"RTS1"
_U32 = struct.Struct("<I")

# Local-handoff rings (same-process writer/reader), keyed by channel name.
_local_rings: dict = {}
_local_lock = threading.Lock()


class _LocalRing:
    def __init__(self):
        self.items: list = []
        self.closed = False
        self.cond = threading.Condition()


def _leaf_meta(leaf) -> tuple:
    """(shape, np.dtype, size_elems) of an array leaf (jax or numpy)."""
    dtype = np.dtype(leaf.dtype)
    size = 1
    for d in leaf.shape:
        size *= int(d)
    return tuple(leaf.shape), dtype, size


def _chunk_elems(dtype: np.dtype, chunk_bytes: int) -> int:
    return max(1, chunk_bytes // max(1, dtype.itemsize))


def _host_resident(leaf) -> bool:
    """True when a jax Array's bytes are already host-addressable (CPU
    backend): streaming then slices ONE host view instead of dispatching a
    device slice + transfer per chunk."""
    try:
        return all(d.platform == "cpu" for d in leaf.devices())
    except Exception:
        return False


def _norm_index(index, shape) -> tuple:
    """Shard index (tuple of slices) -> ((start, stop), ...) over every dim."""
    out = []
    for dim in range(len(shape)):
        if dim < len(index):
            sl = index[dim]
            start = 0 if sl.start is None else int(sl.start)
            stop = shape[dim] if sl.stop is None else int(sl.stop)
        else:
            start, stop = 0, shape[dim]
        out.append((start, stop))
    return tuple(out)


def _shard_plan(leaf):
    """Per-shard send plan for a mesh-sharded jax Array, or None for the
    single-buffer path. Returns [(bounds, shard)] with replicated device
    copies deduped and a deterministic bounds order, so the reader can
    reassemble (or re-scatter) without any metadata beyond the header.

    This is the no-gather half of the sharded PD handoff
    (docs/serving_tp.md): each shard's bytes leave ITS device directly — a
    flat-reshape slice over the global array would force XLA to gather the
    whole tensor onto one device first, which may not even fit when the
    model needs the mesh to exist at all."""
    jax = sys.modules.get("jax")
    if jax is None or not isinstance(leaf, jax.Array):
        return None
    try:
        if len(leaf.sharding.device_set) <= 1:
            return None
        fully_addressable = leaf.is_fully_addressable
        shards = leaf.addressable_shards
    except Exception:
        return None
    if not fully_addressable:
        raise ValueError(
            "cannot stream a partially-addressable sharded array: a "
            "DeviceChannel moves one process's shards (multi-host arrays "
            "stream per host from the process that owns them)"
        )
    shape = tuple(leaf.shape)
    seen = {}
    for shard in shards:
        bounds = _norm_index(shard.index, shape)
        if bounds not in seen:
            seen[bounds] = shard
    if len(seen) <= 1:
        return None  # fully replicated: any one copy IS the array
    return sorted(seen.items(), key=lambda kv: kv[0])


def _assemble_sharded(shape, dtype, bounds_list, shard_hosts, sharding):
    """Rebuild a streamed sharded leaf on the consumer.

    With a target `sharding` whose device->index map covers exactly the
    streamed bounds, each shard host buffer is `device_put` onto its OWN
    target device(s) and the global array assembles zero-gather via
    `jax.make_array_from_single_device_arrays`. Any mismatch (different TP
    degree, replicated target, no sharding given) assembles host-side and
    pays one explicit placement copy — correctness never depends on the
    layouts agreeing."""
    import jax

    if sharding is not None:
        try:
            imap = sharding.addressable_devices_indices_map(tuple(shape))
            by_bounds: dict = {}
            for dev, idx in imap.items():
                by_bounds.setdefault(_norm_index(idx, shape), []).append(dev)
            if set(by_bounds) == set(bounds_list):
                arrays = []
                for bounds, host in zip(bounds_list, shard_hosts):
                    for dev in by_bounds[bounds]:
                        arrays.append(jax.device_put(host, dev))
                return jax.make_array_from_single_device_arrays(
                    tuple(shape), sharding, arrays
                )
        except Exception:
            pass  # layout mismatch or older jax: the host path below is exact
    out = np.empty(shape, dtype)
    for bounds, host in zip(bounds_list, shard_hosts):
        out[tuple(slice(lo, hi) for lo, hi in bounds)] = host
    if sharding is not None:
        return jax.device_put(out, sharding)
    return jax.device_put(out)


class DeviceChannel:
    """One-writer one-reader stream of array trees over a chunked transport.

    `transport=None` is the local (same-process) mode; otherwise a `Channel`
    (same-node shm) or `RpcChannel` (cross-node) carries the frames. The
    object pickles by transport identity, so a writer can mint a channel and
    ship the reader end through any control-plane message."""

    def __init__(self, transport=None, chunk_bytes: Optional[int] = None,
                 name: Optional[str] = None):
        if chunk_bytes is None:
            from ray_tpu._private.config import CONFIG

            chunk_bytes = CONFIG.llm_channel_chunk_bytes
        self._transport = transport
        self._chunk = int(chunk_bytes)
        self._name = name or f"rtpudev_{uuid.uuid4().hex[:12]}"
        if transport is None:
            with _local_lock:
                _local_rings.setdefault(self._name, _LocalRing())

    @classmethod
    def create(cls, *, same_node: bool = True, local: bool = False,
               chunk_bytes: Optional[int] = None,
               num_slots: Optional[int] = None,
               owner=None) -> "DeviceChannel":
        from ray_tpu._private.config import CONFIG

        chunk = chunk_bytes or CONFIG.llm_channel_chunk_bytes
        if local:
            return cls(None, chunk)
        slots = num_slots or CONFIG.devobj_stream_slots
        # Headroom past the chunk size: the header frame (pickled skeleton +
        # leaf descriptors) rides the same ring.
        capacity = int(chunk) + (64 << 10)
        if same_node:
            transport = Channel(capacity, num_readers=1, num_slots=slots)
        else:
            transport = RpcChannel(capacity, num_readers=1, num_slots=slots,
                                   owner=owner)
        return cls(transport, chunk)

    def __reduce__(self):
        return (DeviceChannel, (self._transport, self._chunk, self._name))

    # -- local mode --------------------------------------------------------
    def _local(self) -> _LocalRing:
        with _local_lock:
            ring = _local_rings.get(self._name)
        if ring is None:
            raise RuntimeError(
                "local DeviceChannel crossed a process boundary: same-process "
                "handoff requires writer and reader in one process — use "
                "create(same_node=...) for cross-process streams"
            )
        return ring

    # -- writer ------------------------------------------------------------
    def send(self, value: Any, *, sharding=None,
             timeout: Optional[float] = None,
             on_stall: Optional[Callable] = None):
        """Stream `value`'s array leaves to the reader.

        Local mode: the arrays are handed over by reference — with a
        `sharding`, via `jax.device_put(x, sharding)` so XLA moves the bytes
        over ICI to the target devices; no host staging.

        Transport mode: one header frame, then each leaf's bytes as chunk
        frames. jax leaves are sliced ON DEVICE and fetched chunk-at-a-time,
        so the D2H leg pipelines with the wire leg through the ring.

        `on_stall` (multicast dead-subscriber unwind): when a frame write
        times out, it is invoked with no args; returning True means "the
        blocker was removed, RESUME the same frame" (the stream stays
        consistent for the remaining readers — a restarted send would tear
        it), anything else re-raises the TimeoutError."""
        if self._transport is None:
            item = value
            if sharding is not None:
                import jax

                item = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, sharding), value
                )
            ring = self._local()
            with ring.cond:
                if ring.closed:
                    raise ChannelClosed()
                ring.items.append(item)
                ring.cond.notify_all()
            return
        skeleton_bytes, leaves = _tt.split(value, 0)
        plans = [_shard_plan(leaf) for leaf in leaves]
        descs = []
        for leaf, plan in zip(leaves, plans):
            shape, dtype, size = _leaf_meta(leaf)
            if plan is None:
                descs.append((shape, dtype, size))
            else:
                # Sharded leaf: the desc carries the shard bounds, and the
                # payload frames follow in exactly this shard order.
                descs.append((shape, dtype, size, [b for b, _ in plan]))
        meta = pickle.dumps(
            (skeleton_bytes, descs, self._chunk),
            protocol=pickle.HIGHEST_PROTOCOL,
        )

        def write_frame(data):
            """One frame write, resumable across stall-unwound subscribers:
            write_bytes never partially commits a slot, so retrying the SAME
            frame after on_stall() detached the blocker keeps the stream
            byte-identical for every remaining reader."""
            while True:
                try:
                    return self._transport.write_bytes(data, timeout)
                except TimeoutError:
                    if on_stall is None or not on_stall():
                        raise

        write_frame(STREAM_MAGIC + _U32.pack(len(meta)) + meta)
        rpc = isinstance(self._transport, RpcChannel)
        jax = sys.modules.get("jax")
        for leaf, desc, plan in zip(leaves, descs, plans):
            _shape, dtype, size = desc[:3]
            ce = _chunk_elems(dtype, self._chunk)
            if plan is not None:
                for _bounds, shard in plan:
                    # Per-shard D2H: bytes leave each shard's own device —
                    # never a cross-device gather of the global array.
                    host = np.ascontiguousarray(np.asarray(shard.data))  # raylint: disable=RL603 (the per-shard D2H leg itself — one local pull per shard IS the point)
                    flatb = _tt.as_flat_bytes(host)
                    isz = dtype.itemsize
                    ssize = host.size
                    for a in range(0, ssize, ce):
                        b = min(ssize, a + ce)
                        mv = flatb[a * isz : b * isz].data
                        _tt.note("stream_chunks_staged")
                        write_frame(bytes(mv) if rpc else mv)
                continue
            if (jax is not None and isinstance(leaf, jax.Array)
                    and not _host_resident(leaf)):
                flat = jax.numpy.reshape(leaf, (-1,))
                for a in range(0, size, ce):
                    # Chunked D2H: one slice transfer per frame; the ring
                    # back-pressures, so at most `num_slots` chunks of host
                    # staging exist at once.
                    chunk = np.asarray(flat[a : min(size, a + ce)])  # raylint: disable=RL603 (the chunked D2H leg itself — one bounded slice per frame IS the point)
                    _tt.note("stream_chunks_staged")
                    write_frame(
                        bytes(chunk.view(np.uint8).data) if rpc
                        else _tt.as_flat_bytes(chunk).data
                    )
            else:
                if not isinstance(leaf, np.ndarray):
                    # CPU-backed jax array: ONE host view (zero-copy on the
                    # CPU backend), then plain buffer slices — per-chunk
                    # device slicing would pay a jax dispatch per frame for
                    # bytes that are already host-addressable.
                    leaf = np.asarray(leaf)
                flatb = _tt.as_flat_bytes(np.ascontiguousarray(leaf))
                isz = dtype.itemsize
                for a in range(0, size, ce):
                    b = min(size, a + ce)
                    mv = flatb[a * isz : b * isz].data
                    _tt.note("stream_chunks_staged")
                    write_frame(bytes(mv) if rpc else mv)
        # One logical tensor frame per stream in the fast-path accounting
        # (the per-chunk byte counts land via the transport's write_bytes).
        _tt.note("tensor_frames_written")
        from ray_tpu.experimental.channel import _metric

        try:
            _metric("chan_tensor_fastpath_total").inc()
        except Exception:
            pass  # observability must never break the stream

    # -- reader ------------------------------------------------------------
    def recv(self, *, on_chunk: Optional[Callable] = None,
             assemble: bool = True, timeout: Optional[float] = None) -> Any:
        """Read one streamed value.

        Default: assemble each leaf into a host numpy array and return the
        joined tree. `on_chunk(leaf_idx, elt_offset, typed_chunk)` is invoked
        per chunk AS FRAMES ARRIVE — over shm the chunk is a ZERO-COPY view
        of the ring slot, valid only for the duration of the callback (copy
        or device_put before returning). With assemble=False only the
        callback sees the payload and array leaves join as None (pure
        streaming consumers: PD attach staging, progress tees)."""
        if self._transport is None:
            ring = self._local()
            deadline = None if timeout is None else time.monotonic() + timeout
            with ring.cond:
                while not ring.items:
                    if ring.closed:
                        raise ChannelClosed()
                    wait = 0.1
                    if deadline is not None:
                        wait = min(wait, deadline - time.monotonic())
                        if wait <= 0:
                            raise TimeoutError("device channel recv timed out")
                    ring.cond.wait(wait)
                return ring.items.pop(0)
        header = self._transport.read_bytes(timeout)
        if bytes(header[:4]) != STREAM_MAGIC:
            raise ValueError(
                "device channel stream out of sync: expected a header frame"
            )
        (meta_len,) = _U32.unpack_from(header, 4)
        skeleton_bytes, descs, chunk_bytes = pickle.loads(
            memoryview(header)[8 : 8 + meta_len]
        )
        shm = isinstance(self._transport, Channel)
        leaves: List[Optional[np.ndarray]] = []
        for li, desc in enumerate(descs):
            shape, dtype, size = desc[:3]
            ce = _chunk_elems(dtype, chunk_bytes)

            def read_flat(n_elems, out_buf, li=li):
                """Drain one flat segment of n_elems from the stream into
                out_buf (None = discard); on_chunk offsets are segment-local."""
                for a in range(0, n_elems, ce):
                    b = min(n_elems, a + ce)
                    if shm:
                        view = self._transport.read_view(timeout)
                        typed = None
                        try:
                            typed = np.frombuffer(view.mv, dtype=dtype)
                            if out_buf is not None:
                                out_buf[a:b] = typed
                            if on_chunk is not None:
                                on_chunk(li, a, typed)
                        finally:
                            del typed  # drop the slot alias before the ack
                            view.release()
                    else:
                        data = self._transport.read_bytes(timeout)
                        typed = np.frombuffer(data, dtype=dtype)
                        if out_buf is not None:
                            out_buf[a:b] = typed
                        if on_chunk is not None:
                            on_chunk(li, a, typed)

            if len(desc) == 4:
                # Sharded leaf (docs/serving_tp.md): one flat segment per
                # shard, assembled into its bounds of the global array.
                out = np.empty(shape, dtype) if assemble else None
                for bounds in desc[3]:
                    sshape = tuple(hi - lo for lo, hi in bounds)
                    ssize = 1
                    for d in sshape:
                        ssize *= d
                    buf = np.empty(ssize, dtype) if assemble else None
                    read_flat(ssize, buf)
                    if assemble:
                        out[tuple(slice(lo, hi) for lo, hi in bounds)] = (
                            buf.reshape(sshape)
                        )
                leaves.append(out if assemble else None)
            else:
                out = np.empty(size, dtype) if assemble else None
                read_flat(size, out)
                leaves.append(out.reshape(shape) if assemble else None)
        return _tt.join(skeleton_bytes, leaves)

    def recv_device(self, timeout: Optional[float] = None, *,
                    sharding=None) -> Any:
        """Read one streamed value with per-chunk DEVICE staging: each chunk
        is `jax.device_put` as it arrives (H2D overlaps the wire/D2H legs),
        then leaves assemble on device with one concatenate+reshape — the
        host never holds a full copy of any leaf.

        `sharding` (optional) is the consumer's target mesh layout
        (docs/serving_tp.md): shard frames whose bounds match the target's
        device->index map stage each shard straight onto ITS device and
        assemble with `jax.make_array_from_single_device_arrays` — the
        no-scatter half of the sharded PD handoff. Mismatched layouts fall
        back to host assembly + one `jax.device_put(..., sharding)`
        (correct, one resharding copy).

        Dtypes follow jax's x64 rules on the receiving process (int64/float64
        chunks downcast unless jax_enable_x64 is on); use recv() when the
        consumer needs bitwise host fidelity for wide dtypes."""
        import jax
        import jax.numpy as jnp

        if self._transport is None:
            value = self.recv(timeout=timeout)
            if sharding is not None:
                value = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, sharding), value
                )
            return value
        header = self._transport.read_bytes(timeout)
        if bytes(header[:4]) != STREAM_MAGIC:
            raise ValueError(
                "device channel stream out of sync: expected a header frame"
            )
        (meta_len,) = _U32.unpack_from(header, 4)
        skeleton_bytes, descs, chunk_bytes = pickle.loads(
            memoryview(header)[8 : 8 + meta_len]
        )
        shm = isinstance(self._transport, Channel)

        def read_host_flat(n_elems, dtype, ce):
            """One flat segment, assembled host-side (owned buffers)."""
            out = np.empty(n_elems, dtype)
            for a in range(0, n_elems, ce):
                b = min(n_elems, a + ce)
                if shm:
                    view = self._transport.read_view(timeout)
                    try:
                        out[a:b] = np.frombuffer(view.mv, dtype=dtype)
                    finally:
                        view.release()
                else:
                    out[a:b] = np.frombuffer(
                        self._transport.read_bytes(timeout), dtype=dtype
                    )
            return out

        leaves = []
        for desc in descs:
            shape, dtype, size = desc[:3]
            ce = _chunk_elems(dtype, chunk_bytes)
            if len(desc) == 4:
                bounds_list = desc[3]
                shard_hosts = []
                for bounds in bounds_list:
                    sshape = tuple(hi - lo for lo, hi in bounds)
                    ssize = 1
                    for d in sshape:
                        ssize *= d
                    shard_hosts.append(
                        read_host_flat(ssize, dtype, ce).reshape(sshape)
                    )
                leaves.append(_assemble_sharded(
                    shape, dtype, bounds_list, shard_hosts, sharding
                ))
                continue
            chunks = []
            for a in range(0, size, ce):
                if shm:
                    view = self._transport.read_view(timeout)
                    try:
                        # Owned host copy before device_put: the CPU backend
                        # may alias host memory, and the slot recycles at
                        # release.
                        host = np.frombuffer(view.mv, dtype=dtype).copy()
                    finally:
                        view.release()
                else:
                    host = np.frombuffer(
                        self._transport.read_bytes(timeout), dtype=dtype
                    )
                chunks.append(jax.device_put(host))
            if not chunks:
                flat = jnp.zeros((0,), dtype)
            elif len(chunks) == 1:
                flat = chunks[0]
            else:
                flat = jnp.concatenate(chunks)
            leaf = jnp.reshape(flat, shape)
            if sharding is not None:
                leaf = jax.device_put(leaf, sharding)
            leaves.append(leaf)
        return _tt.join(skeleton_bytes, leaves)

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        if self._transport is None:
            ring = self._local()
            with ring.cond:
                ring.closed = True
                ring.cond.notify_all()
            return
        self._transport.close()

    def drain(self, timeout: Optional[float] = None) -> bool:
        if self._transport is None:
            return True
        return self._transport.drain(timeout)

    def destroy(self):
        if self._transport is None:
            with _local_lock:
                _local_rings.pop(self._name, None)
            return
        self._transport.destroy()


class Subscription:
    """One subscriber's end of a multicast stream (leaksan-tracked).

    Obtained via `MulticastDeviceChannel.subscribe(i)` in the SUBSCRIBER's
    process; `unsubscribe()` releases the slot — it detaches the reader from
    ring back-pressure, so a subscriber that is done (or bailing early) can
    never wedge the writer or its siblings. An unreleased subscription is a
    live leaksan handle (`mc_subscription`)."""

    __slots__ = ("_chan", "_transport", "index", "group", "_active",
                 "__weakref__")

    def __init__(self, group: str, transport, chunk_bytes: int, index: int):
        self._transport = transport
        self._chan = DeviceChannel(transport, chunk_bytes)
        self.index = int(index)
        self.group = group
        self._active = True
        from ray_tpu.devtools import leaksan as _leaksan

        _leaksan.track(
            "mc_subscription", self,
            detail=f"subscriber {index} of {group}",
        )

    def recv(self, **kw):
        """One streamed value, host-assembled (DeviceChannel.recv)."""
        return self._chan.recv(**kw)

    def recv_device(self, timeout=None, *, sharding=None):
        """One streamed value with per-chunk device staging
        (DeviceChannel.recv_device)."""
        return self._chan.recv_device(timeout, sharding=sharding)

    def unsubscribe(self):
        """Idempotent release: detach this reader slot from the ring's
        back-pressure accounting and drop the stream view."""
        if not self._active:
            return
        self._active = False
        try:
            self._transport.detach_reader(self.index)
        except Exception:
            pass  # writer already gone: nothing back-pressures anymore
        self._chan = None
        from ray_tpu.devtools import leaksan as _leaksan

        _leaksan.untrack("mc_subscription", self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.unsubscribe()


class MulticastDeviceChannel:
    """One-writer N-subscriber fanout over ONE chunked transport ring.

    The point (docs/device_channels.md): `send()` stages each payload chunk
    out of the source array exactly ONCE (one D2H pass on accelerators —
    `stream_chunks_staged` in transport_stats() proves it) and the ring's
    per-reader ack words fan the same slot bytes out to every subscriber. A
    slow subscriber back-pressures the writer through its own ack (never
    corrupts siblings); a DEAD subscriber is unwound with `detach(i)` (writer
    side) or `Subscription.unsubscribe()` (reader side), after which the
    writer and the remaining subscribers proceed.

    Used by the PD plane so one prefill replica feeds every decode replica in
    a group with a single D2H pass (pd_disagg.prefill_multicast). The object
    pickles by transport identity: mint it writer-side, ship it through any
    control-plane message, and have each subscriber call `subscribe(i)` with
    its assigned index."""

    def __init__(self, transport, chunk_bytes: int, num_subscribers: int,
                 name: Optional[str] = None):
        self._transport = transport
        self._chunk = int(chunk_bytes)
        self.num_subscribers = int(num_subscribers)
        self._name = name or f"rtpumc_{uuid.uuid4().hex[:12]}"
        self._writer = DeviceChannel(transport, chunk_bytes)
        self.detached: set = set()  # writer-side record of unwound subscribers

    @classmethod
    def create(cls, num_subscribers: int, *, same_node: bool = True,
               chunk_bytes: Optional[int] = None,
               num_slots: Optional[int] = None,
               owner=None) -> "MulticastDeviceChannel":
        from ray_tpu._private.config import CONFIG

        if num_subscribers < 1:
            raise ValueError("a multicast group needs at least one subscriber")
        chunk = chunk_bytes or CONFIG.llm_channel_chunk_bytes
        slots = num_slots or CONFIG.devobj_stream_slots
        capacity = int(chunk) + (64 << 10)
        if same_node:
            transport = Channel(capacity, num_readers=num_subscribers,
                                num_slots=slots)
        else:
            transport = RpcChannel(capacity, num_readers=num_subscribers,
                                   num_slots=slots, owner=owner)
        return cls(transport, chunk, num_subscribers)

    def __reduce__(self):
        return (MulticastDeviceChannel,
                (self._transport, self._chunk, self.num_subscribers,
                 self._name))

    # -- writer ------------------------------------------------------------
    def send(self, value: Any, timeout: Optional[float] = None,
             stall_timeout: Optional[float] = None):
        """Stream `value` once; every live subscriber receives it.

        `timeout` bounds each frame write by the SLOWEST live subscriber's
        ack (plain back-pressure; a TimeoutError aborts the send). With
        `stall_timeout` set instead, a frame write stalled that long detaches
        the lagging subscriber(s) — presumed dead — and RESUMES the same
        frame, so the writer unwinds without wedging (or tearing the stream
        for) the remaining subscribers; `self.detached` records who was
        unwound."""
        if stall_timeout is None:
            self._writer.send(value, timeout=timeout)
            return

        def unwind() -> bool:
            lagging = [
                r for r in self._transport.lagging_readers()
                if r not in self.detached
            ]
            for r in lagging:
                self.detach(r)
            return bool(lagging)

        self._writer.send(value, timeout=stall_timeout, on_stall=unwind)

    def detach(self, index: int):
        """Writer-side dead-subscriber unwind: stop waiting on subscriber
        `index` forever. The remaining subscribers are untouched."""
        self.detached.add(index)
        self._transport.detach_reader(index)

    def drain(self, timeout: Optional[float] = None) -> bool:
        return self._transport.drain(timeout)

    # -- subscribers -------------------------------------------------------
    def subscribe(self, index: int) -> Subscription:
        """Bind subscriber slot `index` in the CALLING process. Pair with
        `unsubscribe()` (leaklint RL801 enforces it)."""
        if not 0 <= index < self.num_subscribers:
            raise ValueError(
                f"subscriber index {index} out of range "
                f"[0, {self.num_subscribers})"
            )
        return Subscription(self._name, self._transport.reader(index),
                            self._chunk, index)

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        self._transport.close()

    def destroy(self):
        self._transport.destroy()
