"""Actor API: @ray_tpu.remote on classes, ActorClass / ActorHandle / ActorMethod.

Design parity: reference `python/ray/actor.py` (ActorClass._remote :1498, ActorHandle
:1857, ActorMethod._remote :792) — named actors, namespaces, get_if_exists, max_restarts,
max_concurrency (threaded) and async actors (async def methods → asyncio event loop with
a concurrency semaphore), ordered per-caller method delivery.
"""

from __future__ import annotations

import inspect

from ray_tpu._private.ids import ActorID
from ray_tpu._private.worker import global_worker
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.remote_function import _build_pg_spec, _build_resources, _resolve_scheduling

_ACTOR_DEFAULTS = {
    "num_cpus": 0,
    "num_tpus": 0,
    "memory": None,  # bytes; schedulable + enforced via cgroup-v2 where active
    "resources": None,
    "name": None,
    "namespace": None,
    "get_if_exists": False,
    "lifetime": None,
    "max_restarts": 0,
    "max_concurrency": None,
    "concurrency_groups": None,
    "allow_out_of_order_execution": False,
    "placement_group": None,
    "placement_group_bundle_index": 0,
    "scheduling_strategy": None,
    "max_retries": None,
    "num_returns": 1,
    "runtime_env": None,
}


def _public_methods(cls) -> list[str]:
    names = []
    for name, member in inspect.getmembers(cls, predicate=callable):
        if not name.startswith("_") or name == "__call__":
            names.append(name)
    return names


def _declared_method_opts(cls) -> dict:
    """Collect @ray_tpu.method declarations: name -> opts dict."""
    out = {}
    for name, member in inspect.getmembers(cls, predicate=callable):
        opts = getattr(member, "__ray_tpu_method_opts__", None)
        if opts:
            out[name] = dict(opts)
    return out


def _has_async_methods(cls) -> bool:
    return any(
        inspect.iscoroutinefunction(m) or inspect.isasyncgenfunction(m)
        for _n, m in inspect.getmembers(cls, predicate=inspect.isfunction)
    )


def method(num_returns: int = 1, concurrency_group: str | None = None):
    """Method decorator (reference: @ray.method, python/ray/actor.py): bind a
    method to a declared concurrency group and/or set its return arity.
    Bare `@method` (no parentheses) decorates with the defaults."""

    def wrap(fn):
        fn.__ray_tpu_method_opts__ = {
            "num_returns": num_returns,
            "concurrency_group": concurrency_group,
        }
        return fn

    if callable(num_returns):
        fn, num_returns = num_returns, 1
        return wrap(fn)
    return wrap


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int | None = None,
                 concurrency_group: str | None = None):
        self._handle = handle
        self._method_name = method_name
        declared = handle._method_opts.get(method_name, {})
        self._num_returns = (
            num_returns if num_returns is not None
            else declared.get("num_returns", 1)
        )
        self._concurrency_group = (
            concurrency_group if concurrency_group is not None
            else declared.get("concurrency_group")
        )

    def options(self, num_returns: int | None = None,
                concurrency_group: str | None = None, **_ignored):
        return ActorMethod(
            self._handle, self._method_name, num_returns, concurrency_group
        )

    def remote(self, *args, **kwargs):
        worker = global_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id, self._method_name, args, kwargs,
            self._num_returns, concurrency_group=self._concurrency_group,
            out_of_order=self._handle._out_of_order,
        )
        if self._num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a compiled-graph node for this method call (reference:
        actor_method.bind() -> ClassMethodNode, python/ray/dag/class_node.py)."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    def __init__(
        self,
        actor_id: ActorID,
        method_names: list[str],
        class_name: str = "",
        method_opts: dict | None = None,
        out_of_order: bool = False,
        _owns_arg_pins: bool = False,
    ):
        self._actor_id = actor_id
        self._method_names = list(method_names)
        self._class_name = class_name
        self._out_of_order = out_of_order
        # method name -> {"num_returns": n, "concurrency_group": g} from
        # @ray_tpu.method declarations (travels with serialized handles).
        self._method_opts = dict(method_opts or {})
        # Only the handle returned to the CREATOR guards the actor's pinned init
        # args; deserialized copies (__reduce__) do not, so a borrower dropping
        # its copy cannot release pins it never took.
        self._owns_arg_pins = _owns_arg_pins

    def __del__(self):
        # GC-safe: defer (finalizers must not take runtime locks; see
        # ReferenceCounter.defer_remove).
        if getattr(self, "_owns_arg_pins", False):
            try:
                from ray_tpu._private.worker import global_worker_or_none

                w = global_worker_or_none()
                if w is not None:
                    w.reference_counter.defer_actor_pin_release(self._actor_id)
            except Exception:
                pass  # interpreter shutdown

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if self._method_names and name not in self._method_names:
            raise AttributeError(
                f"actor {self._class_name or self._actor_id} has no method {name!r}"
            )
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._method_names, self._class_name,
             self._method_opts, self._out_of_order),
        )


class ActorClass:
    def __init__(self, cls, options: dict):
        self._cls = cls
        self._options = {**_ACTOR_DEFAULTS, **options}
        self._cls_key = None

    def options(self, **overrides) -> "ActorClass":
        clone = ActorClass(self._cls, {**self._options, **overrides})
        clone._cls_key = self._cls_key
        return clone

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = global_worker()
        if self._cls_key is None or getattr(self, "_cls_session", None) != worker.session_token:
            self._cls_key = worker.functions.export(self._cls)
            self._cls_session = worker.session_token
        opts = self._options
        strategy, opts = _resolve_scheduling(opts)
        is_async = _has_async_methods(self._cls)
        max_concurrency = opts["max_concurrency"] or (1000 if is_async else 1)
        namespace = opts["namespace"]
        if namespace is None:
            import ray_tpu

            namespace = ray_tpu._current_namespace()
        from ray_tpu._private import runtime_env as runtime_env_mod

        method_names = _public_methods(self._cls)
        method_opts = _declared_method_opts(self._cls)
        cgroups = dict(opts["concurrency_groups"] or {})
        method_groups = {}
        for mname, mopts in method_opts.items():
            group = mopts.get("concurrency_group")
            if group is not None:
                if group not in cgroups:
                    raise ValueError(
                        f"method {mname!r} is bound to concurrency group "
                        f"{group!r} but the actor declares only "
                        f"{sorted(cgroups)} (pass concurrency_groups= to "
                        f"@ray_tpu.remote)"
                    )
                method_groups[mname] = group
        actor_id, owns_pins = worker.create_actor(
            cls_key=self._cls_key,
            class_name=self._cls.__name__,
            args=args,
            kwargs=kwargs,
            name=opts["name"],
            namespace=namespace,
            get_if_exists=opts["get_if_exists"],
            resources=_build_resources(opts),
            placement_group=_build_pg_spec(opts),
            max_restarts=opts["max_restarts"],
            max_concurrency=max_concurrency,
            is_async=is_async,
            scheduling_strategy=strategy,
            method_names=method_names,
            runtime_env=runtime_env_mod.validate(opts.get("runtime_env")),
            concurrency_groups=cgroups,
            method_groups=method_groups,
            method_opts=method_opts,
            allow_out_of_order_execution=opts["allow_out_of_order_execution"],
        )
        return ActorHandle(
            actor_id, method_names, self._cls.__name__, method_opts,
            out_of_order=opts["allow_out_of_order_execution"],
            _owns_arg_pins=owns_pins,
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()"
        )


def get_actor(name: str, namespace: str | None = None) -> ActorHandle:
    worker = global_worker()
    if namespace is None:
        import ray_tpu

        namespace = ray_tpu._current_namespace()
    info = worker.gcs_call("get_actor_info", None, name, namespace)
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"actor {name!r} not found in namespace {namespace!r}")
    return ActorHandle(
        info["actor_id"],
        info.get("method_names") or [],
        info.get("class_name") or "",
        info.get("method_opts"),
        out_of_order=info.get("out_of_order", False),
    )


def kill(actor: ActorHandle, no_restart: bool = True):
    worker = global_worker()
    worker.gcs_call("kill_actor", actor._actor_id, no_restart)


def exit_actor():
    """Terminate the current actor process (parity: ray.actor.exit_actor)."""
    import os

    worker = global_worker()
    if worker.actor_id is None:
        raise RuntimeError("exit_actor called outside an actor")
    os._exit(0)
