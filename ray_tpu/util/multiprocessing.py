"""multiprocessing.Pool-compatible API over the distributed runtime.

Parity: reference `python/ray/util/multiprocessing/pool.py` — Pool with
map/starmap/imap/imap_unordered/apply/apply_async over remote tasks, so existing
`multiprocessing` code scales past one machine by changing an import.
`processes` is honored as a true concurrency cap (at most that many chunks in
flight), and the initializer runs once per worker process before any work — the
standard multiprocessing contract.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

# Worker-process-side: initializers that already ran here (keyed by identity).
_initialized: set = set()


def _run_chunk(fn, arg_tuples: List[tuple], initializer=None, initargs=()) -> List[Any]:
    if initializer is not None:
        key = (getattr(initializer, "__module__", ""),
               getattr(initializer, "__qualname__", repr(initializer)))
        if key not in _initialized:
            initializer(*initargs)
            _initialized.add(key)
    return [fn(*args) for args in arg_tuples]


class AsyncResult:
    """Windowed executor: keeps at most `window` chunk tasks in flight."""

    def __init__(self, task, chunk_args: List[tuple], window: int,
                 single: bool = False, flatten: bool = True):
        self._refs: List = []
        self._single = single
        self._flatten = flatten
        self._total = len(chunk_args)
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

        def run():
            inflight: List = []
            try:
                for args in chunk_args:
                    while len(inflight) >= window:
                        _ready, rest = ray_tpu.wait(inflight, num_returns=1,
                                                    timeout=None)
                        inflight = list(rest)
                    ref = task.remote(*args)
                    self._refs.append(ref)
                    inflight.append(ref)
            except BaseException as e:
                self._error = e
            finally:
                self._done.set()

        threading.Thread(target=run, daemon=True).start()

    def _ref_at(self, i: int, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while i >= len(self._refs):
            if self._done.is_set() and i >= len(self._refs):
                if self._error is not None:
                    raise self._error
                raise IndexError(i)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("result not ready")
            time.sleep(0.005)
        return self._refs[i]

    def get(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        if not self._done.wait(timeout):
            raise TimeoutError("pool tasks still submitting")
        if self._error is not None:
            raise self._error
        remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
        chunks = ray_tpu.get(self._refs, remaining)
        if self._single:
            return chunks[0][0]
        if self._flatten:
            return list(itertools.chain.from_iterable(chunks))
        return chunks

    def wait(self, timeout: Optional[float] = None):
        if self._done.wait(timeout):
            ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        if not self._done.is_set():
            return False
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False

    def iter_ordered(self):
        i = 0
        while True:
            try:
                ref = self._ref_at(i)
            except IndexError:
                return
            yield from ray_tpu.get(ref)
            i += 1

    def iter_unordered(self):
        seen: set = set()
        while True:
            self._done.wait(0.005)
            pending = [r for r in self._refs if r.id not in seen]
            if not pending:
                if self._done.is_set():
                    if self._error is not None:
                        raise self._error
                    return
                continue
            ready, _ = ray_tpu.wait(pending, num_returns=1, timeout=1)
            for ref in ready:
                seen.add(ref.id)
                yield from ray_tpu.get(ref)


class Pool:
    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), **_kwargs):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cpus = ray_tpu.cluster_resources().get("CPU", 1)
        self._size = processes or max(1, int(cpus))
        self._initializer = initializer
        self._initargs = tuple(initargs)
        self._closed = False
        self._chunk_task = ray_tpu.remote(num_cpus=1)(_run_chunk)

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, items: List[tuple], chunksize: Optional[int]) -> List[tuple]:
        chunksize = chunksize or max(1, len(items) // (self._size * 4) or 1)
        return [
            (items[c : c + chunksize],)
            for c in range(0, len(items), chunksize)
        ]

    def _submit(self, fn, arg_tuples: List[tuple], chunksize, single=False,
                flatten=True) -> AsyncResult:
        self._check_open()
        chunk_args = [
            (fn, chunk[0], self._initializer, self._initargs)
            for chunk in self._chunks(arg_tuples, chunksize)
        ]
        return AsyncResult(self._chunk_task, chunk_args, self._size,
                           single=single, flatten=flatten)

    def apply(self, fn: Callable, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args=(), kwds=None) -> AsyncResult:
        kwds = dict(kwds or {})
        call = (lambda *a: fn(*a, **kwds)) if kwds else fn
        return self._submit(call, [tuple(args)], chunksize=1, single=True)

    def map(self, fn: Callable, iterable: Iterable, chunksize: Optional[int] = None):
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._submit(fn, [(i,) for i in iterable], chunksize)

    def starmap(self, fn: Callable, iterable: Iterable[tuple], chunksize=None):
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._submit(fn, [tuple(t) for t in iterable], chunksize)

    def imap(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        return self._submit(fn, [(i,) for i in iterable], chunksize).iter_ordered()

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        return self._submit(fn, [(i,) for i in iterable], chunksize).iter_unordered()

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
