"""State API: observability over cluster entities.

Parity: reference `python/ray/util/state/api.py` (`ray list tasks|actors|nodes|...`,
`ray summary tasks`) backed by GCS tables + task events (the GcsTaskManager role,
`src/ray/gcs/gcs_task_manager.h`).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional

import ray_tpu


def _gcs(*args):
    return ray_tpu.global_worker().gcs_call(*args)


def list_nodes() -> List[Dict[str, Any]]:
    return _gcs("get_nodes")


def list_actors(*, filters=None) -> List[Dict[str, Any]]:
    actors = _gcs("list_actors")
    if filters:
        for key, op, value in filters:
            assert op == "=", "only '=' filters are supported"
            actors = [a for a in actors if str(a.get(key)) == str(value)]
    return actors


def list_tasks(*, limit: int = 1000, filters=None) -> List[Dict[str, Any]]:
    events = _gcs("list_task_events", limit)
    if filters:
        for key, op, value in filters:
            assert op == "=", "only '=' filters are supported"
            events = [e for e in events if str(e.get(key)) == str(value)]
    return events


def list_objects(*, limit: int = 1000) -> List[Dict[str, Any]]:
    return _gcs("list_objects", limit)


def list_placement_groups() -> List[Dict[str, Any]]:
    return _gcs("list_placement_groups")


def list_jobs() -> List[Dict[str, Any]]:
    from ray_tpu.job_submission import JobSubmissionClient

    return JobSubmissionClient._attached().list_jobs()


def summarize_tasks() -> Dict[str, int]:
    """Task counts by LATEST state per task (parity: `ray summary tasks`).

    Events are a log flushed per-worker on independent timers, so both list order
    and arrival order interleave; the per-event `time` field decides latest."""
    latest: Dict[str, tuple] = {}
    for e in list_tasks(limit=100_000):
        tid = e.get("task_id")
        if tid is None:
            continue
        t = e.get("time", 0.0)
        if tid not in latest or t >= latest[tid][0]:
            latest[tid] = (t, e.get("state", "UNKNOWN"))
    return dict(Counter(state for _t, state in latest.values()))


def summarize_actors() -> Dict[str, int]:
    by_state: Counter = Counter()
    for a in list_actors():
        by_state[a.get("state", "UNKNOWN")] += 1
    return dict(by_state)


def cluster_summary() -> Dict[str, Any]:
    nodes = list_nodes()
    return {
        "nodes": len(nodes),
        "alive_nodes": sum(1 for n in nodes if n.get("alive", True)),
        "resources_total": ray_tpu.cluster_resources(),
        "resources_available": ray_tpu.available_resources(),
        "tasks": summarize_tasks(),
        "actors": summarize_actors(),
    }


__all__ = [
    "cluster_summary",
    "list_actors",
    "list_jobs",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_tasks",
    "summarize_actors",
    "summarize_tasks",
]
