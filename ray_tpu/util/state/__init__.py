"""State API: observability over cluster entities.

Parity: reference `python/ray/util/state/api.py` (`ray list tasks|actors|nodes|...`,
`ray summary tasks`) backed by GCS tables + task events (the GcsTaskManager role,
`src/ray/gcs/gcs_task_manager.h`).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional

import ray_tpu


def _gcs(*args):
    return ray_tpu.global_worker().gcs_call(*args)


from ray_tpu._private.state_filters import build_predicate


def _apply_filters(rows: List[Dict[str, Any]], filters) -> List[Dict[str, Any]]:
    """Filter triples (key, op, value) with the reference's predicate set
    (python/ray/util/state/common.py supports =/!= plus comparisons). The
    predicate implementation is shared with the GCS's pushed-down task-event
    query (ray_tpu/_private/state_filters.py)."""
    if not filters:
        return rows
    match = build_predicate(filters)
    return [r for r in rows if match(r)]


def _paginate(rows: List[Dict[str, Any]], limit: Optional[int], offset: int):
    if offset:
        rows = rows[offset:]
    if limit is not None:
        rows = rows[:limit]
    return rows


def list_nodes(*, filters=None, limit: Optional[int] = None,
               offset: int = 0) -> List[Dict[str, Any]]:
    return _paginate(_apply_filters(_gcs("get_nodes"), filters), limit, offset)


def list_actors(*, filters=None, limit: Optional[int] = None,
                offset: int = 0) -> List[Dict[str, Any]]:
    return _paginate(
        _apply_filters(_gcs("list_actors"), filters), limit, offset
    )


def get_actor(actor_id_hex: str) -> Optional[Dict[str, Any]]:
    """Per-entity drill-down (parity: `ray get actors <id>`)."""
    for a in _gcs("list_actors"):
        aid = a.get("actor_id")
        if (aid.hex() if hasattr(aid, "hex") else str(aid)) == actor_id_hex:
            return a
    return None


def list_tasks(*, limit: Optional[int] = 1000, filters=None,
               offset: int = 0) -> List[Dict[str, Any]]:
    """Filters and pagination are PUSHED DOWN to the GCS (round 5): a
    filtered `ray_tpu list tasks` scans server-side with early exit and
    ships only the matching page, instead of fetching the whole retention
    window into the client (reference: GcsTaskManager query filters)."""
    if limit is not None and limit <= 0:
        return []  # the wire encodes "no limit" as 0; an explicit 0 is empty
    return _gcs("list_task_events", limit or 0, list(filters or ()), offset)


def get_task(task_id_hex: str) -> List[Dict[str, Any]]:
    """Per-entity drill-down: every recorded event of one task, time-ordered,
    served from the GCS's per-task index."""
    events = _gcs("list_task_events", 0, None, 0, task_id_hex)
    return sorted(events, key=lambda e: e.get("time", 0.0))


def list_objects(*, limit: int = 1000) -> List[Dict[str, Any]]:
    return _gcs("list_objects", limit)


def list_placement_groups() -> List[Dict[str, Any]]:
    return _gcs("list_placement_groups")


def list_jobs() -> List[Dict[str, Any]]:
    from ray_tpu.job_submission import JobSubmissionClient

    return JobSubmissionClient._attached().list_jobs()


def summarize_tasks() -> Dict[str, int]:
    """Task counts by LATEST state per task (parity: `ray summary tasks`).

    Events are a log flushed per-worker on independent timers, so both list order
    and arrival order interleave; the per-event `time` field decides latest."""
    latest: Dict[str, tuple] = {}
    for e in list_tasks(limit=100_000):
        tid = e.get("task_id")
        if tid is None:
            continue
        t = e.get("time", 0.0)
        if tid not in latest or t >= latest[tid][0]:
            latest[tid] = (t, e.get("state", "UNKNOWN"))
    return dict(Counter(state for _t, state in latest.values()))


def summarize_actors() -> Dict[str, int]:
    by_state: Counter = Counter()
    for a in list_actors():
        by_state[a.get("state", "UNKNOWN")] += 1
    return dict(by_state)


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Export task events as Chrome trace-event JSON (parity: `ray timeline`,
    reference python/ray/scripts/scripts.py + GcsTaskManager events). The
    output loads in Perfetto / chrome://tracing.

    Lanes: one pid per worker (scheduling spans on the submitting worker's
    lane, execution spans on the executing worker's)."""
    import json

    events = list_tasks(limit=100_000)
    per_task: Dict[str, Dict[str, Any]] = {}
    workers: Dict[str, int] = {}

    def lane(worker_hex: Optional[str]) -> int:
        key = worker_hex or "?"
        return workers.setdefault(key, len(workers) + 1)

    for e in events:
        tid = e.get("task_id")
        if tid is None:
            continue
        rec = per_task.setdefault(tid, {"name": e.get("name", "?")})
        rec[e.get("state", "UNKNOWN")] = e
    trace: List[Dict[str, Any]] = []
    for tid, rec in per_task.items():
        sub, run = rec.get("SUBMITTED"), rec.get("RUNNING")
        end = rec.get("FINISHED") or rec.get("FAILED")
        if sub and run:
            trace.append({
                "name": f"schedule:{rec['name']}", "cat": "scheduling",
                "ph": "X", "ts": sub["time"] * 1e6,
                "dur": max(run["time"] - sub["time"], 0) * 1e6,
                "pid": lane(sub.get("worker_id")), "tid": 0,
                "args": {"task_id": tid},
            })
        if run and end:
            trace.append({
                "name": rec["name"],
                "cat": "task",
                "ph": "X", "ts": run["time"] * 1e6,
                "dur": max(end["time"] - run["time"], 0) * 1e6,
                "pid": lane(run.get("worker_id")), "tid": 0,
                "args": {
                    "task_id": tid,
                    "state": "FAILED" if rec.get("FAILED") else "FINISHED",
                    **{k: run.get(k) for k in ("trace_id", "span_id")
                       if run.get(k)},
                },
            })
    meta = [
        {"name": "process_name", "ph": "M", "pid": idx,
         "args": {"name": f"worker {hex_[:12]}"}}
        for hex_, idx in workers.items()
    ]
    out = meta + sorted(trace, key=lambda ev: ev["ts"])
    if filename:
        with open(filename, "w") as f:
            json.dump(out, f)
    return out


def memory_summary(*, limit: int = 10_000) -> Dict[str, Any]:
    """Object-store contents grouped by owner (parity: `ray memory`,
    reference python/ray/_private/internal_api.py memory_summary)."""
    objects = list_objects(limit=limit)
    by_owner: Dict[str, Dict[str, Any]] = {}
    total = 0
    for o in objects:
        size = o.get("size") or 0
        total += size
        key = o.get("owner_worker_id") or "?"
        agg = by_owner.setdefault(key, {"count": 0, "bytes": 0})
        agg["count"] += 1
        agg["bytes"] += size
    return {
        "num_objects": len(objects),
        "total_bytes": total,
        # The directory listing is capped: totals cover only what's listed.
        "truncated": len(objects) >= limit,
        "by_owner": by_owner,
        "objects": objects,
    }


def get_log(worker_id_hex: str, *, tail: int = 200) -> List[str]:
    """Recent output lines of one worker (parity: `ray logs worker*` /
    util/state get_log — served from the GCS's per-worker log ring, which the
    driver-streaming path already feeds)."""
    return _gcs("get_worker_log", worker_id_hex, tail)


def list_export_events(directory: Optional[str] = None, *,
                       source_type: Optional[str] = None) -> List[Dict[str, Any]]:
    """Read structured export events written by the GCS when
    RAY_TPU_EXPORT_EVENTS_DIR is set (the aggregator role of the reference's
    dashboard/modules/aggregator over export_*.proto records)."""
    import glob
    import json
    import os

    from ray_tpu._private.config import CONFIG

    directory = directory or CONFIG.export_events_dir
    if not directory or not os.path.isdir(directory):
        return []
    pattern = (
        f"export_{source_type}.jsonl" if source_type else "export_*.jsonl"
    )
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(directory, pattern))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail line: the GCS is mid-append
    out.sort(key=lambda r: r.get("timestamp", 0.0))
    return out


import threading as _threading

_CP_METRICS: Dict[str, Any] = {}  # lazy util.metrics handles, report-path only
_CP_METRICS_LOCK = _threading.Lock()


def control_plane_stats() -> Dict[str, Any]:
    """Store + replication-plane counters of the current GCS primary, and the
    ONLY place they become util.metrics series (gcs_store_append_seconds,
    gcs_store_log_bytes, gcs_store_compactions_total,
    gcs_repl_lag_records{peer}, gcs_failovers_total).

    The GCS process keeps plain counters and never touches metrics objects —
    a metrics flush is itself a GCS KV RPC, so flushing from the append or
    replication paths would re-enter the control plane from inside it (the
    docs/raylint.md leaksan teardown-deadlock lesson). Calling this report
    path is what surfaces the series."""
    stats = _gcs("store_stats")
    try:
        from ray_tpu.util.metrics import Gauge

        def gauge(name: str, desc: str, tag_keys=None) -> Any:
            with _CP_METRICS_LOCK:
                g = _CP_METRICS.get(name)
                if g is None:
                    g = _CP_METRICS[name] = Gauge(name, desc,
                                                  tag_keys=tag_keys)
            return g

        store = stats.get("store") or {}
        repl = stats.get("repl") or {}
        gauge("gcs_store_append_seconds",
              "cumulative seconds the GCS primary spent appending to its "
              "durable log").set(float(store.get("append_seconds", 0.0)))
        gauge("gcs_store_log_bytes",
              "current size of the GCS primary's append log").set(
                  float(store.get("log_bytes", 0)))
        gauge("gcs_store_compactions_total",
              "append-log snapshot rewrites since the primary started").set(
                  float(store.get("compactions", 0)))
        gauge("gcs_failovers_total",
              "primary promotions past the cluster's first election").set(
                  float(repl.get("failovers", 0)))
        lag_gauge = gauge("gcs_repl_lag_records",
                          "records each follower candidate trails the "
                          "primary's log head by", tag_keys=("peer",))
        for peer, lag in (repl.get("lag") or {}).items():
            lag_gauge.set(float(lag), tags={"peer": str(peer)})
    except Exception:
        pass  # observability must never break the stats read itself
    return stats


def serve_stats(timeout_s: float = 30.0) -> Dict[str, Any]:
    """ONE operator snapshot of the whole serve plane (docs/observability.md).

    Aggregates the stats surfaces that previously required five separate
    calls — per-app `scheduler_stats()` / `adapter_stats()` /
    `routing_stats()` / `cache_stats()` / `recorder_stats()` from the
    ingress deployments, the process-local transport counters
    (`transport_stats()`), and the GCS `control_plane_stats()` — into one
    dict keyed by app. Best-effort per surface: an app whose ingress lacks a
    given stats method simply omits that key (an OpenAI router in front of
    plain LLMServers exposes fewer surfaces than a DPRouter), and a briefly
    unreachable surface records its error string instead of failing the
    snapshot. Calling it is a REPORT path: each engine's pending SLO metrics
    and trace spans flush as a side effect of `recorder_stats()` /
    `scheduler_stats()`."""
    from ray_tpu import serve
    from ray_tpu.serve.handle import DeploymentHandle

    out: Dict[str, Any] = {"apps": {}}
    try:
        apps = serve.status()
    except Exception as e:
        apps = {}
        out["error"] = str(e)
    surfaces = ("scheduler_stats", "adapter_stats", "routing_stats",
                "cache_stats", "recorder_stats")
    for app, meta in apps.items():
        ingress = (meta or {}).get("ingress")
        if not ingress:
            continue
        app_stats: Dict[str, Any] = {"ingress": ingress}
        try:
            handle = DeploymentHandle(app, ingress)
            for surface in surfaces:
                try:
                    app_stats[surface] = getattr(handle, surface).remote(
                    ).result(timeout_s=timeout_s)
                except Exception:
                    continue  # ingress doesn't expose this surface
        except Exception as e:
            app_stats["error"] = str(e)
        out["apps"][app] = app_stats
    try:
        from ray_tpu.experimental.tensor_transport import transport_stats

        out["transport"] = transport_stats()
    except Exception as e:
        out["transport"] = {"error": str(e)}
    try:
        out["control_plane"] = control_plane_stats()
    except Exception as e:
        out["control_plane"] = {"error": str(e)}
    try:
        from ray_tpu.serve import _existing_controller

        controller = _existing_controller()
        if controller is not None:
            out["autopilot"] = ray_tpu.get(
                controller.autopilot_stats.remote(), timeout=timeout_s)
    except Exception as e:
        out["autopilot"] = {"error": str(e)}
    return out


def cluster_summary() -> Dict[str, Any]:
    nodes = list_nodes()
    return {
        "nodes": len(nodes),
        "alive_nodes": sum(1 for n in nodes if n.get("alive", True)),
        "resources_total": ray_tpu.cluster_resources(),
        "resources_available": ray_tpu.available_resources(),
        "tasks": summarize_tasks(),
        "actors": summarize_actors(),
    }


def capture_profile(targets, duration_s: float = 3.0,
                    out_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """On-demand fleet profiling: start a `jax.profiler` trace capture on each
    target simultaneously, wait `duration_s`, and gather the trace artifacts
    back to the driver.

    A target is either a serve APP NAME (string — resolved to its ingress
    deployment, so a DPRouter app fans the capture out to every replica) or an
    ACTOR HANDLE exposing ``capture_profile(duration_s)`` (train workers do:
    `WorkerGroup.sorted_workers`). All captures are launched before any result
    is awaited so the traces cover the same wall-clock window. Each row is
    ``{"target", "capture"}`` (capture = the worker's
    ``ray_tpu.util.xprof.capture`` dict, or a list of them for a fanned-out
    app) or ``{"target", "error"}``. With ``out_dir`` the gathered trace file
    bytes are also written under ``out_dir/<target>[/rank]/`` and each row
    gains a ``"gathered"`` list of the paths written."""
    import os

    from ray_tpu import serve
    from ray_tpu.serve.handle import DeploymentHandle

    pending: List[tuple] = []  # (label, kind, future-or-error)
    try:
        apps = serve.status()
    except Exception:
        apps = {}
    for i, target in enumerate(targets):
        if isinstance(target, str):
            ingress = (apps.get(target) or {}).get("ingress")
            if not ingress:
                pending.append((target, "error",
                                f"no serve app named {target!r}"))
                continue
            try:
                handle = DeploymentHandle(target, ingress)
                fut = handle.capture_profile.remote(duration_s)
                pending.append((target, "serve", fut))
            except Exception as e:
                pending.append((target, "error", str(e)))
        else:
            try:
                ref = target.capture_profile.remote(duration_s)
                pending.append((f"actor-{i}", "actor", ref))
            except Exception as e:
                pending.append((f"actor-{i}", "error", str(e)))
    gather_timeout = duration_s + 60.0
    rows: List[Dict[str, Any]] = []
    for label, kind, obj in pending:
        row: Dict[str, Any] = {"target": label}
        try:
            if kind == "error":
                row["error"] = obj
            elif kind == "serve":
                row["capture"] = obj.result(timeout_s=gather_timeout)
            else:
                row["capture"] = ray_tpu.get(obj, timeout=gather_timeout)
        except Exception as e:
            row["error"] = str(e)
        rows.append(row)
    if out_dir:
        for row in rows:
            cap = row.get("capture")
            if cap is None:
                continue
            caps = cap if isinstance(cap, list) else [cap]
            gathered: List[str] = []
            for j, c in enumerate(caps):
                if not isinstance(c, dict):
                    continue
                sub = os.path.join(out_dir, str(row["target"]).replace("/", "_"))
                if len(caps) > 1:
                    sub = os.path.join(sub, f"rank{c.get('dp_rank', j)}")
                for rel, data in (c.get("files") or {}).items():
                    path = os.path.join(sub, rel)
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    with open(path, "wb") as f:
                        f.write(data)
                    gathered.append(path)
            row["gathered"] = gathered
    return rows


def cluster_status(timeout_s: float = 30.0) -> Dict[str, Any]:
    """Everything `ray_tpu status` renders, as one dict: the cluster summary
    (nodes / resources / task+actor states), the per-node and per-actor
    listings, the serve-plane snapshot (which itself carries transport and
    control-plane stats and, per app, each engine's program registry and
    device-memory ledger), and the DRIVER-side xprof reports. Calling it is a
    report path — registry counters and ledger gauges flush here, never from
    dispatch paths."""
    from ray_tpu.util import xprof

    out: Dict[str, Any] = {"summary": cluster_summary()}
    out["nodes"] = list_nodes()
    try:
        out["actors"] = list_actors(limit=200)
    except Exception as e:
        out["actors"] = [{"error": str(e)}]
    out["serve"] = serve_stats(timeout_s=timeout_s)
    out["programs"] = xprof.registry().report()
    out["memory"] = xprof.device_memory_report()
    return out


__all__ = [
    "capture_profile",
    "cluster_status",
    "cluster_summary",
    "control_plane_stats",
    "get_actor",
    "get_log",
    "get_task",
    "list_actors",
    "list_export_events",
    "list_jobs",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_tasks",
    "memory_summary",
    "serve_stats",
    "summarize_actors",
    "summarize_tasks",
    "timeline",
]
