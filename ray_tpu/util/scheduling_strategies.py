"""Scheduling strategies.

Design parity: reference `python/ray/util/scheduling_strategies.py` (:17
PlacementGroupSchedulingStrategy, :43 NodeAffinitySchedulingStrategy).
"""

from __future__ import annotations


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group, placement_group_bundle_index: int = 0,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: dict | None = None, soft: dict | None = None):
        self.hard = hard or {}
        self.soft = soft or {}
