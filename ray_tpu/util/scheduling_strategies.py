"""Scheduling strategies.

Design parity: reference `python/ray/util/scheduling_strategies.py` (:17
PlacementGroupSchedulingStrategy, :43 NodeAffinitySchedulingStrategy).
"""

from __future__ import annotations


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group, placement_group_bundle_index: int = 0,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class In:
    """Label value must be one of `values` (reference :123 label operators)."""

    def __init__(self, *values):
        self.values = [str(v) for v in values]

    def to_spec(self):
        return {"op": "in", "values": self.values}


class NotIn:
    def __init__(self, *values):
        self.values = [str(v) for v in values]

    def to_spec(self):
        return {"op": "not_in", "values": self.values}


class Exists:
    def to_spec(self):
        return {"op": "exists"}


class DoesNotExist:
    def to_spec(self):
        return {"op": "absent"}


def _selector_spec(selector: dict) -> dict:
    """{key: op|plain-value} -> wire form (plain values mean In(value))."""
    out = {}
    for key, op in (selector or {}).items():
        out[key] = op.to_spec() if hasattr(op, "to_spec") else In(op).to_spec()
    return out


def match_labels(node_labels: dict, selector: dict) -> bool:
    """Evaluate a wire-form selector against a node's label map (reference:
    `node_label_scheduling_policy.cc` hard-match semantics)."""
    labels = {str(k): str(v) for k, v in (node_labels or {}).items()}
    for key, op in (selector or {}).items():
        kind = op.get("op")
        present = key in labels
        if kind == "exists":
            if not present:
                return False
        elif kind == "absent":
            if present:
                return False
        elif kind == "in":
            if not present or labels[key] not in op.get("values", ()):
                return False
        elif kind == "not_in":
            if present and labels[key] in op.get("values", ()):
                return False
        else:
            return False
    return True


class NodeLabelSchedulingStrategy:
    """Schedule onto nodes matching label selectors (reference :123-148).

    hard: every expression must match; soft: preferred but not required.
    Values may be plain strings (equality) or In/NotIn/Exists/DoesNotExist."""

    def __init__(self, hard: dict | None = None, soft: dict | None = None):
        self.hard = hard or {}
        self.soft = soft or {}

    def to_spec(self) -> dict:
        return {"labels": {"hard": _selector_spec(self.hard),
                           "soft": _selector_spec(self.soft)}}


class CompositeSchedulingStrategy:
    """First-satisfiable-wins over sub-strategies (e.g. a label selector OR
    plain resource scheduling when no labeled node exists). Reference shape:
    composite policies layered over node_label_scheduling_policy.cc."""

    def __init__(self, any_of: list):
        if not any_of:
            raise ValueError("composite needs at least one sub-strategy")
        self.any_of = list(any_of)

    def to_spec(self) -> dict:
        subs = []
        for s in self.any_of:
            if s is None:
                subs.append({})  # plain resource scheduling
            elif hasattr(s, "to_spec"):
                subs.append(s.to_spec())
            elif isinstance(s, NodeAffinitySchedulingStrategy):
                subs.append({"node_id": s.node_id, "soft": s.soft})
            else:
                raise TypeError(f"unsupported composite member {type(s).__name__}")
        return {"composite": subs}
