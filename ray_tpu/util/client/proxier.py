"""Client proxy: one public endpoint fronting a whole cluster for thin clients.

Design parity: reference `python/ray/util/client/server/proxier.py` — a dedicated
proxy process that terminates every external client connection, tracks per-client
sessions, and isolates clients from each other and from the cluster's internal
ports. Re-designed for this runtime's symmetric framed-RPC protocol instead of
gRPC: each tunneled connection opens with a length-prefixed JSON routing
envelope `{"route": [host, port], "client_id": ..., "token": ...}` (written by
`rpc.connect(via=...)`), the proxy validates the target against the cluster's
registered raylet/GCS endpoints (exact host:port), dials it, and relays frames
verbatim in both directions. Per-client isolation properties:

- clients never learn or reach GCS/raylet/worker ports directly — only the
  proxy's single public port needs to be reachable (the proxier's main job);
- every client's tunnels are separate upstream TCP connections tagged with its
  client_id; one client's disconnect tears down exactly its own tunnels, and
  the upstream raylet/GCS observe the drop and run their normal driver-death
  cleanup (leases released, owned objects freed);
- a control channel (`{"control": true}` envelope) serves ping/list_clients/
  stats for operators, the reference proxier's Datapath bookkeeping role;
- the proxy process never unpickles client bytes: envelopes and control frames
  are JSON, tunneled frames are relayed opaquely. (The reference runs one
  "SpecificServer" subprocess per client because its server must deserialize
  client payloads; here that happens only in the client process and in task
  workers.)

Trust boundary, stated honestly: relayed frames ARE this runtime's pickled RPC
protocol, and the upstream GCS/raylet unpickle them — exactly as they do for
any in-cluster peer. The proxy therefore restricts WHO can reach those ports
(optional shared `token`, checked before any dial) and WHERE they can dial
(exact registered endpoints), but a client that passes both is trusted the way
an in-cluster driver is. Expose the proxy port to networks you would let run
drivers, not the open internet.
"""

from __future__ import annotations

import asyncio
import json
import struct
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import rpc as _rpc

_LEN_FMT = "<Q"


async def _read_json_frame(reader: asyncio.StreamReader, max_len: int = 1 << 16) -> Any:
    header = await reader.readexactly(8)
    (length,) = struct.unpack(_LEN_FMT, header)
    if length > max_len:
        raise ValueError("oversized envelope")
    return json.loads(await reader.readexactly(length))


def _json_frame(msg: Any) -> bytes:
    payload = json.dumps(msg).encode()
    return struct.pack(_LEN_FMT, len(payload)) + payload


class _ClientSession:
    __slots__ = ("client_id", "connected_at", "last_seen", "tunnels", "bytes_up",
                 "bytes_down")

    def __init__(self, client_id: str):
        self.client_id = client_id
        self.connected_at = time.time()
        self.last_seen = self.connected_at
        self.tunnels = 0
        self.bytes_up = 0
        self.bytes_down = 0

    def snapshot(self) -> Dict[str, Any]:
        return {
            "client_id": self.client_id,
            "connected_at": self.connected_at,
            "last_seen": self.last_seen,
            "tunnels": self.tunnels,
            "bytes_up": self.bytes_up,
            "bytes_down": self.bytes_down,
        }


class ClientProxy:
    """Accepts tunneled client connections and relays them to validated cluster
    endpoints. Run via `serve_proxy()` or the `ray_tpu client-proxy` CLI."""

    def __init__(self, gcs_addr: Tuple[str, int], *, host: str = "127.0.0.1",
                 port: int = 0, node_cache_s: Optional[float] = None,
                 token: Optional[str] = None):
        self._gcs_addr = (gcs_addr[0], int(gcs_addr[1]))
        self._token = token
        self._host = host
        self._requested_port = port
        from ray_tpu._private.config import CONFIG

        self._node_cache_s = (
            node_cache_s if node_cache_s is not None
            else CONFIG.client_proxy_node_cache_s
        )
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self._sessions: Dict[str, _ClientSession] = {}
        self._allowed: set = set()
        self._allowed_at = 0.0

    # ------------------------------------------------------------------ server
    async def start(self) -> "ClientProxy":
        self._server = await asyncio.start_server(
            self._on_conn, self._host, self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def close(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ------------------------------------------------------------ target policy
    async def _refresh_allowed(self):
        """Exact-endpoint allowlist: the GCS itself plus every registered
        raylet address. Thin clients only ever dial those two service classes
        (remote_data_plane disables worker-direct fast paths), so anything
        else — including other ports on cluster hosts — is refused. This is
        what keeps the proxy from being a generic TCP relay."""
        now = time.monotonic()
        if now - self._allowed_at < self._node_cache_s and self._allowed:
            return
        conn = await _rpc.connect(*self._gcs_addr, name="proxy-nodes")
        try:
            nodes = await conn.call("get_nodes")
        finally:
            await conn.close()
        allowed = {self._gcs_addr}
        for n in nodes:
            addr = n.get("address")
            if addr:
                allowed.add((addr[0], int(addr[1])))
        self._allowed = allowed
        self._allowed_at = now

    async def _target_allowed(self, target: Tuple[str, int]) -> bool:
        endpoint = (target[0], int(target[1]))
        if endpoint == self._gcs_addr:
            return True
        await self._refresh_allowed()
        return endpoint in self._allowed

    # ------------------------------------------------------------------ relays
    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            envelope = await asyncio.wait_for(_read_json_frame(reader), 15)
        except Exception:
            writer.close()
            return
        if not isinstance(envelope, dict):
            writer.close()
            return
        if self._token is not None:
            import hmac

            presented = envelope.get("token")
            if not isinstance(presented, str) or not hmac.compare_digest(
                presented.encode(), self._token.encode()
            ):
                writer.close()
                return
        if envelope.get("control"):
            await self._serve_control(reader, writer)
            return
        route = envelope.get("route")
        if isinstance(route, list):
            route = tuple(route)
        client_id = str(envelope.get("client_id", "anonymous"))
        if isinstance(route, tuple) and len(route) == 2 and route[0] == "gcs":
            # Symbolic target: proxy clients know only the proxy's address; the
            # proxy substitutes its configured GCS (clients never see it).
            route = self._gcs_addr
        try:
            if (not isinstance(route, tuple) or len(route) != 2
                    or not await self._target_allowed(route)):
                writer.close()
                return
            up_reader, up_writer = await asyncio.wait_for(
                asyncio.open_connection(route[0], int(route[1])), 15
            )
        except Exception:
            # Validation itself can fail transiently (GCS restarting): fail the
            # tunnel fast with a reset rather than wedging the client half-open.
            writer.close()
            return
        sess = self._sessions.get(client_id)
        if sess is None:
            sess = self._sessions[client_id] = _ClientSession(client_id)
        sess.tunnels += 1

        async def pump(src, dst, up: bool):
            try:
                while True:
                    chunk = await src.read(1 << 16)
                    if not chunk:
                        break
                    dst.write(chunk)
                    await dst.drain()
                    sess.last_seen = time.time()
                    if up:
                        sess.bytes_up += len(chunk)
                    else:
                        sess.bytes_down += len(chunk)
            except Exception:
                pass  # either side hung up: the finally below closes the tunnel
            finally:
                try:
                    dst.close()
                except Exception:
                    pass

        try:
            await asyncio.gather(
                pump(reader, up_writer, True), pump(up_reader, writer, False)
            )
        finally:
            sess.tunnels -= 1
            if sess.tunnels <= 0:
                # Last tunnel gone: the client is disconnected. Upstream
                # raylet/GCS conns just closed with it, which triggers their
                # normal driver-disconnect cleanup; drop the session record.
                self._sessions.pop(client_id, None)

    # ----------------------------------------------------------------- control
    async def _serve_control(self, reader, writer):
        """Tiny framed request/response loop for operators and tests."""
        try:
            while True:
                req = await _read_json_frame(reader)
                op = req.get("op")
                if op == "ping":
                    resp = {"ok": True, "gcs": self._gcs_addr}
                elif op == "list_clients":
                    resp = {"clients": [s.snapshot() for s in self._sessions.values()]}
                elif op == "stats":
                    resp = {
                        "num_clients": len(self._sessions),
                        "num_tunnels": sum(s.tunnels for s in self._sessions.values()),
                    }
                else:
                    resp = {"error": f"unknown op {op!r}"}
                writer.write(_json_frame(resp))
                await writer.drain()
        except Exception:
            pass  # malformed/aborted ops connection: just drop it
        finally:
            try:
                writer.close()
            except Exception:
                pass


def serve_proxy(gcs_addr: Tuple[str, int], *, host: str = "127.0.0.1",
                port: int = 0, token: Optional[str] = None,
                insecure: bool = False) -> Tuple[ClientProxy, Any]:
    """Start a proxy on a private IO loop; returns (proxy, io_loop). Blocking
    callers (CLI) should then sleep/join; tests use proxy.port.

    Binding a non-loopback host without a token is refused unless
    ``insecure=True``: any peer that can reach the port would get
    in-cluster-driver trust (relayed frames are the cluster's pickled RPC
    protocol)."""
    if host not in ("127.0.0.1", "::1", "localhost") and not token and not insecure:
        raise ValueError(
            f"refusing to bind {host} without a token: any peer that can "
            "reach the port gets in-cluster-driver trust. Pass token=..., "
            "or insecure=True to override on a trusted network."
        )
    loop = _rpc.IoLoop(name="client-proxy")
    proxy = ClientProxy(gcs_addr, host=host, port=port, token=token)
    loop.run(proxy.start(), 30)
    return proxy, loop


def control_call(proxy_addr: Tuple[str, int], op: str, timeout: float = 10.0,
                 token: Optional[str] = None) -> dict:
    """One-shot control request against a running proxy (CLI/tests)."""
    import socket

    env = {"control": True}
    if token:
        env["token"] = token
    with socket.create_connection(proxy_addr, timeout=timeout) as s:
        s.sendall(_json_frame(env))
        s.sendall(_json_frame({"op": op}))
        header = _recv_exact(s, 8)
        (length,) = struct.unpack(_LEN_FMT, header)
        return json.loads(_recv_exact(s, length))


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("proxy closed control connection")
        buf += chunk
    return buf
