"""Ray-Client-style remote connectivity.

`proxier.ClientProxy` is the dedicated proxy process (reference:
`python/ray/util/client/server/proxier.py`) that fronts a cluster for
`ray_tpu+proxy://` thin clients.
"""

from ray_tpu.util.client.proxier import ClientProxy, serve_proxy

__all__ = ["ClientProxy", "serve_proxy"]
