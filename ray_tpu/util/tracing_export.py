"""OpenTelemetry export for distributed traces.

Design parity: reference `python/ray/util/tracing/tracing_helper.py:36-60` —
spans recorded around remote calls flow to an OpenTelemetry backend. Here spans
already ride the task-event pipeline (util/tracing.py: every event of a traced
call carries trace_id/span_id/parent_span_id), so export is a pure transform:
pair each task's RUNNING -> FINISHED/FAILED events into spans and emit them as
OTLP. Two sinks, no SDK dependency:

- `export_otlp_http(endpoint)` POSTs OTLP/JSON to any OpenTelemetry collector's
  HTTP receiver (`/v1/traces`), built with urllib only — works wherever an
  otel-collector is reachable, regardless of which otel packages are installed.
- `export_otlp_file(path)` writes the same OTLP/JSON payload to disk (replay
  with `otel-cli` / collector `filelogreceiver`, or inspect directly).

If the full `opentelemetry-sdk` happens to be installed, `spans_to_otel(spans)`
also re-emits them through the user's configured global TracerProvider, so
existing OTel pipelines (sampling, processors) apply unchanged.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Dict, List, Optional

_UNSET = 0  # OTLP enums (trace/v1/trace.proto): STATUS_CODE_UNSET
_ERROR = 2  # STATUS_CODE_ERROR


def spans_from_task_events(events: List[dict]) -> List[dict]:
    """Pair per-task lifecycle events into spans. Only traced events (those
    carrying a trace_id) produce spans; SUBMITTED time is attached as the
    queueing attribute when present."""
    starts: Dict[str, dict] = {}
    submitted: Dict[str, dict] = {}
    spans: List[dict] = []
    for e in events:
        if not e.get("trace_id"):
            continue
        tid = e.get("task_id")
        state = e.get("state")
        if state == "SUBMITTED":
            submitted[tid] = e
        elif state == "RUNNING":
            starts[tid] = e
        elif state in ("FINISHED", "FAILED") and tid in starts:
            s = starts.pop(tid)
            sub = submitted.pop(tid, None)
            spans.append({
                "trace_id": s["trace_id"],
                "span_id": s.get("span_id") or tid[:16],
                "parent_span_id": s.get("parent_span_id"),
                "name": e.get("name") or s.get("name") or "task",
                "start_s": s["time"],
                "end_s": e["time"],
                "ok": state == "FINISHED",
                "attributes": {
                    "ray_tpu.task_id": tid,
                    "ray_tpu.worker_id": s.get("worker_id"),
                    **({"ray_tpu.submitted_s": sub["time"]} if sub else {}),
                },
            })
    return spans


def _otlp_attr(key: str, value) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def to_otlp_json(spans: List[dict], service_name: str = "ray_tpu") -> dict:
    """OTLP/JSON ExportTraceServiceRequest (opentelemetry-proto JSON mapping:
    ids hex-encoded, times in unix nanos as strings)."""
    otlp_spans = []
    for s in spans:
        span = {
            "traceId": s["trace_id"],
            "spanId": s["span_id"],
            "name": s["name"],
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(int(s["start_s"] * 1e9)),
            "endTimeUnixNano": str(int(s["end_s"] * 1e9)),
            "attributes": [
                _otlp_attr(k, v) for k, v in (s.get("attributes") or {}).items()
                if v is not None
            ],
            "status": {"code": _UNSET if s.get("ok", True) else _ERROR},
        }
        if s.get("parent_span_id"):
            span["parentSpanId"] = s["parent_span_id"]
        otlp_spans.append(span)
    return {
        "resourceSpans": [{
            "resource": {"attributes": [_otlp_attr("service.name", service_name)]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.util.tracing"},
                "spans": otlp_spans,
            }],
        }]
    }


def _fetch_events(events: Optional[List[dict]]) -> List[dict]:
    if events is not None:
        return events
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs_call("list_task_events", 100000)


def export_otlp_http(endpoint: str, *, events: Optional[List[dict]] = None,
                     service_name: str = "ray_tpu", timeout: float = 30.0) -> int:
    """POST the cluster's traced spans to an OTLP/HTTP collector. `endpoint` is
    the collector base (e.g. "http://collector:4318") or a full /v1/traces URL.
    Returns the number of spans exported."""
    spans = spans_from_task_events(_fetch_events(events))
    if not spans:
        return 0
    url = endpoint if endpoint.endswith("/v1/traces") else (
        endpoint.rstrip("/") + "/v1/traces"
    )
    body = json.dumps(to_otlp_json(spans, service_name)).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        if resp.status >= 300:
            raise RuntimeError(f"OTLP export failed: HTTP {resp.status}")
    return len(spans)


def export_otlp_file(path: str, *, events: Optional[List[dict]] = None,
                     service_name: str = "ray_tpu") -> int:
    """Write the cluster's traced spans as an OTLP/JSON document."""
    spans = spans_from_task_events(_fetch_events(events))
    with open(path, "w") as f:
        json.dump(to_otlp_json(spans, service_name), f)
    return len(spans)


def spans_to_otel(spans: List[dict]) -> int:
    """Re-emit spans through an installed opentelemetry-sdk TracerProvider (if
    the user configured one); returns spans emitted. Requires the optional
    `opentelemetry-sdk` package — the OTLP/HTTP path above does not."""
    try:
        from opentelemetry import trace as otel_trace
        from opentelemetry.trace import SpanContext, TraceFlags, NonRecordingSpan
        import opentelemetry.context as otel_ctx
    except ImportError as e:  # pragma: no cover - api package is present here
        raise RuntimeError("opentelemetry api not installed") from e
    tracer = otel_trace.get_tracer("ray_tpu.util.tracing")
    n = 0
    for s in spans:
        parent_ctx = None
        if s.get("parent_span_id"):
            parent_ctx = otel_trace.set_span_in_context(NonRecordingSpan(SpanContext(
                trace_id=int(s["trace_id"], 16),
                span_id=int(s["parent_span_id"], 16),
                is_remote=True,
                trace_flags=TraceFlags(TraceFlags.SAMPLED),
            )))
        span = tracer.start_span(
            s["name"], context=parent_ctx,
            start_time=int(s["start_s"] * 1e9),
            attributes={k: v for k, v in (s.get("attributes") or {}).items()
                        if v is not None},
        )
        if not s.get("ok", True):
            from opentelemetry.trace import Status, StatusCode

            span.set_status(Status(StatusCode.ERROR))
        span.end(end_time=int(s["end_s"] * 1e9))
        n += 1
    return n
