"""User-defined metrics: Counter / Gauge / Histogram.

Parity: reference `python/ray/util/metrics.py` — metrics recorded from any worker,
aggregated cluster-wide (the per-node agent → Prometheus pipeline role is played by
the GCS KV store here; `collect_all()` returns the merged series and
`prometheus_text()` renders the exposition format).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

_FLUSH_INTERVAL_S = 2.0
_NAMESPACE = "metrics"


def _worker():
    import ray_tpu

    return ray_tpu.global_worker()


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        self._last_flush = 0.0

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _maybe_flush(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_flush < _FLUSH_INTERVAL_S:
            return
        self._last_flush = now
        try:
            worker = _worker()
            with self._lock:
                payload = {
                    "name": self._name,
                    "type": type(self).__name__.lower(),
                    "description": self._description,
                    "series": [
                        {"tags": dict(k), "value": v} for k, v in self._values.items()
                    ],
                    "ts": time.time(),
                }
            key = f"{self._name}:{worker.worker_id.hex()}".encode()
            worker.gcs_call(
                "kv_put", _NAMESPACE, key, json.dumps(payload).encode(), True
            )
        except Exception:
            pass  # metrics must never break the workload

    def flush(self):
        self._maybe_flush(force=True)


class Counter(Metric):
    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        self._maybe_flush()


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            self._values[key] = value
        self._maybe_flush()


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or [0.1, 1, 10, 100, 1000])

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        base = dict(self._key(tags))
        with self._lock:
            for b in self._boundaries:
                if value <= b:
                    key = tuple(sorted({**base, "le": str(b)}.items()))
                    self._values[key] = self._values.get(key, 0.0) + 1
            inf_key = tuple(sorted({**base, "le": "+Inf"}.items()))
            self._values[inf_key] = self._values.get(inf_key, 0.0) + 1
            sum_key = tuple(sorted({**base, "stat": "sum"}.items()))
            self._values[sum_key] = self._values.get(sum_key, 0.0) + value
        self._maybe_flush()


def collect_all() -> List[dict]:
    """All flushed metric payloads across the cluster (driver-side)."""
    worker = _worker()
    keys = worker.gcs_call("kv_keys", _NAMESPACE, b"")
    out = []
    for key in keys:
        raw = worker.gcs_call("kv_get", _NAMESPACE, key)
        if raw:
            out.append(json.loads(raw))
    return out


def prometheus_text() -> str:
    """Render all metrics in Prometheus exposition format."""
    lines = []
    merged: Dict[Tuple[str, str], Dict[Tuple, float]] = {}
    descs: Dict[str, Tuple[str, str]] = {}
    for payload in collect_all():
        name, mtype = payload["name"], payload["type"]
        descs[name] = (payload.get("description", ""), mtype)
        series = merged.setdefault((name, mtype), {})
        for s in payload["series"]:
            key = tuple(sorted(s["tags"].items()))
            if mtype == "gauge":
                series[key] = s["value"]
            else:
                series[key] = series.get(key, 0.0) + s["value"]
    for (name, mtype), series in merged.items():
        desc, _ = descs[name]
        lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {mtype}")
        for key, value in series.items():
            tags_d = dict(key)
            if mtype == "histogram":
                # Proper exposition: name_bucket{le=...}, name_sum, name_count.
                if tags_d.pop("stat", None) == "sum":
                    base = ",".join(f'{k}="{v}"' for k, v in sorted(tags_d.items()))
                    lines.append(
                        f"{name}_sum{{{base}}} {value}" if base else f"{name}_sum {value}"
                    )
                    continue
                le = tags_d.pop("le", None)
                base_items = sorted(tags_d.items())
                if le is not None:
                    tags = ",".join(
                        f'{k}="{v}"' for k, v in base_items + [("le", le)]
                    )
                    lines.append(f"{name}_bucket{{{tags}}} {value}")
                    if le == "+Inf":
                        base = ",".join(f'{k}="{v}"' for k, v in base_items)
                        lines.append(
                            f"{name}_count{{{base}}} {value}"
                            if base else f"{name}_count {value}"
                        )
                    continue
            tags = ",".join(f'{k}="{v}"' for k, v in key)
            lines.append(f"{name}{{{tags}}} {value}" if tags else f"{name} {value}")
    return "\n".join(lines) + "\n"
