"""User-defined metrics: Counter / Gauge / Histogram.

Parity: reference `python/ray/util/metrics.py` — metrics recorded from any worker,
aggregated cluster-wide (the per-node agent → Prometheus pipeline role is played by
the GCS KV store here; `collect_all()` returns the merged series and
`prometheus_text()` renders the exposition format).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

_FLUSH_INTERVAL_S = 2.0
_NAMESPACE = "metrics"

#: Default Histogram boundaries: a log-spaced latency scale (1 ms to 10 min).
#: The old default ([0.1, 1, 10, 100, 1000]) put every sub-second serving
#: latency in the first bucket — useless for TTFT/TPOT SLOs. Explicit
#: `boundaries=` always overrides.
LATENCY_BUCKETS_S = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 180.0, 600.0,
]


def _worker():
    import ray_tpu

    return ray_tpu.global_worker()


def _note_mutation(name: str):
    """distsan hook: a mutation may flush, and a flush is a blocking GCS
    RPC — record it when a tagged hot/finalizer context is active. One
    enabled() check when the sanitizer is off."""
    from ray_tpu.devtools import distsan

    distsan.note_metric_mutation(name)


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        self._last_flush = 0.0

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _maybe_flush(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_flush < _FLUSH_INTERVAL_S:
            return
        self._last_flush = now
        try:
            worker = _worker()
            with self._lock:
                payload = {
                    "name": self._name,
                    "type": type(self).__name__.lower(),
                    "description": self._description,
                    "series": [
                        {"tags": dict(k), "value": v} for k, v in self._values.items()
                    ],
                    "ts": time.time(),
                }
            key = f"{self._name}:{worker.worker_id.hex()}".encode()
            worker.gcs_call(
                "kv_put", _NAMESPACE, key, json.dumps(payload).encode(), True
            )
        except Exception:
            pass  # metrics must never break the workload

    def flush(self):
        self._maybe_flush(force=True)


class Counter(Metric):
    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        _note_mutation(self._name)
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        self._maybe_flush()


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _note_mutation(self._name)
        key = self._key(tags)
        with self._lock:
            self._values[key] = value
        self._maybe_flush()

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        """Delta mutation (Prometheus up/down-gauge shape; the reference
        Gauge is set-only). For live-occupancy series — active token
        streams, batch in-flight windows — where concurrent reporters can't
        know the absolute value to set()."""
        _note_mutation(self._name)
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        self._maybe_flush()

    def dec(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        self.inc(-value, tags)


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(
            LATENCY_BUCKETS_S if boundaries is None else boundaries
        )

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        _note_mutation(self._name)
        base = dict(self._key(tags))
        with self._lock:
            for b in self._boundaries:
                if value <= b:
                    key = tuple(sorted({**base, "le": str(b)}.items()))
                    self._values[key] = self._values.get(key, 0.0) + 1
            inf_key = tuple(sorted({**base, "le": "+Inf"}.items()))
            self._values[inf_key] = self._values.get(inf_key, 0.0) + 1
            sum_key = tuple(sorted({**base, "stat": "sum"}.items()))
            self._values[sum_key] = self._values.get(sum_key, 0.0) + value
        self._maybe_flush()


def _live_worker_hexes() -> set:
    """Worker ids provably alive right now: this driver plus every actor the
    GCS does not list as DEAD (PENDING counts as live — a loading replica's
    metrics must not be reaped). Plain pooled task workers are not in the
    actor table, so liveness alone never prunes them — the TTL does."""
    alive = set()
    worker = _worker()
    alive.add(worker.worker_id.hex())
    try:
        for a in worker.gcs_call("list_actors"):
            if a.get("state") == "DEAD":
                continue
            wid = (a.get("address") or {}).get("worker_id")
            if wid is not None:
                alive.add(wid.hex() if hasattr(wid, "hex") else str(wid))
    except Exception:
        return alive
    return alive


def collect_all(*, prune: bool = True,
                ttl_s: Optional[float] = None) -> List[dict]:
    """All flushed metric payloads across the cluster (driver-side).

    Dead-series pruning: a payload whose reporting worker is GONE (not this
    driver, no live actor holds its worker id) and whose last flush is older
    than `ttl_s` (default `metrics_series_ttl_s`) is DELETED from the GCS KV
    namespace — without this, every killed replica's gauges live in the
    control plane forever. Live workers' series survive regardless of
    staleness (a quiet counter is not a dead one); `prune=False` restores
    the raw listing."""
    worker = _worker()
    if ttl_s is None:
        from ray_tpu._private.config import CONFIG

        ttl_s = CONFIG.metrics_series_ttl_s
    keys = worker.gcs_call("kv_keys", _NAMESPACE, b"")
    alive = _live_worker_hexes() if prune else set()
    now = time.time()
    out = []
    for key in keys:
        raw = worker.gcs_call("kv_get", _NAMESPACE, key)
        if not raw:
            continue
        payload = json.loads(raw)
        if prune:
            key_str = key.decode() if isinstance(key, bytes) else str(key)
            worker_hex = key_str.rsplit(":", 1)[-1]
            stale = now - float(payload.get("ts", 0.0)) > ttl_s
            if stale and worker_hex not in alive:
                try:
                    worker.gcs_call("kv_del", _NAMESPACE, key)
                except Exception:
                    pass  # best-effort reaping; the entry stays listed-out
                continue
        out.append(payload)
    return out


def render_prometheus() -> str:
    """Render every flushed series from ``collect_all()`` in Prometheus
    exposition format — counters/gauges sum/last-write-win across workers,
    histograms expand to ``_bucket``/``_sum``/``_count`` — so the compute
    plane's gauges are scrapeable without the dashboard."""
    lines = []
    merged: Dict[Tuple[str, str], Dict[Tuple, float]] = {}
    descs: Dict[str, Tuple[str, str]] = {}
    for payload in collect_all():
        name, mtype = payload["name"], payload["type"]
        descs[name] = (payload.get("description", ""), mtype)
        series = merged.setdefault((name, mtype), {})
        for s in payload["series"]:
            key = tuple(sorted(s["tags"].items()))
            if mtype == "gauge":
                series[key] = s["value"]
            else:
                series[key] = series.get(key, 0.0) + s["value"]
    for (name, mtype), series in merged.items():
        desc, _ = descs[name]
        lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {mtype}")
        for key, value in series.items():
            tags_d = dict(key)
            if mtype == "histogram":
                # Proper exposition: name_bucket{le=...}, name_sum, name_count.
                if tags_d.pop("stat", None) == "sum":
                    base = ",".join(f'{k}="{v}"' for k, v in sorted(tags_d.items()))
                    lines.append(
                        f"{name}_sum{{{base}}} {value}" if base else f"{name}_sum {value}"
                    )
                    continue
                le = tags_d.pop("le", None)
                base_items = sorted(tags_d.items())
                if le is not None:
                    tags = ",".join(
                        f'{k}="{v}"' for k, v in base_items + [("le", le)]
                    )
                    lines.append(f"{name}_bucket{{{tags}}} {value}")
                    if le == "+Inf":
                        base = ",".join(f'{k}="{v}"' for k, v in base_items)
                        lines.append(
                            f"{name}_count{{{base}}} {value}"
                            if base else f"{name}_count {value}"
                        )
                    continue
            tags = ",".join(f'{k}="{v}"' for k, v in key)
            lines.append(f"{name}{{{tags}}} {value}" if tags else f"{name} {value}")
    return "\n".join(lines) + "\n"


#: Back-compat alias; `render_prometheus` is the canonical name.
prometheus_text = render_prometheus
