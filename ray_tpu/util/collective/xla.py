"""XLA tier: in-graph device collectives over mesh axes (the NCCL-path replacement).

Where the reference moves device tensors with eager NCCL calls
(`python/ray/util/collective/collective_group/nccl_collective_group.py`), the TPU-native
design expresses device collectives as XLA ops inside jit/shard_map over a
`jax.sharding.Mesh`: the compiler schedules them onto ICI (intra-slice) or DCN
(cross-slice) and overlaps them with compute. This module gives those ops the same verb
vocabulary as the eager API so user code reads uniformly across the two tiers.

Use inside `jax.shard_map` (or any jitted fn with bound axis names):

    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    def step(x):
        g = xla.allreduce(local_grad(x), "dp")
        ...

`MeshGroup` additionally offers *eager* entry points that wrap one collective in a
shard_map and execute it immediately — useful at library boundaries (tests, small sync
points) where building a fused graph isn't worth it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.util.collective.types import ReduceOp
from ray_tpu.util.jax_compat import axis_size as _axis_size, shard_map


def allreduce(x, axis_name, op: ReduceOp = ReduceOp.SUM):
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis_name)
    if op == ReduceOp.MEAN:
        return jax.lax.pmean(x, axis_name)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis_name)
    if op == ReduceOp.PRODUCT:
        # No pprod primitive; exp/sum/log is ill-conditioned, so gather-then-reduce.
        return jnp.prod(jax.lax.all_gather(x, axis_name), axis=0)
    raise ValueError(f"unknown reduce op {op}")


def allgather(x, axis_name, axis: int = 0, tiled: bool = False):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reducescatter(x, axis_name, scatter_axis: int = 0, op: ReduceOp = ReduceOp.SUM):
    if op not in (ReduceOp.SUM, ReduceOp.MEAN):
        raise ValueError("reducescatter supports SUM/MEAN (what XLA lowers natively)")
    out = jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)
    if op == ReduceOp.MEAN:
        out = out / _axis_size(axis_name)
    return out


def ppermute(x, axis_name, perm: list[tuple[int, int]]):
    return jax.lax.ppermute(x, axis_name, perm)


def send_next(x, axis_name):
    """Ring shift: every shard sends to (rank+1) % n. The ring-attention building block."""
    n = _axis_size(axis_name)
    return jax.lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def all_to_all(x, axis_name, split_axis: int, concat_axis: int, tiled: bool = True):
    """Ulysses-style head<->sequence reshard (SURVEY.md §5 long-context)."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


class MeshGroup:
    """Eager wrappers: one collective per call, shard_map-compiled and cached.

    The group's "ranks" are the positions along `axis` of `mesh`; inputs are global
    arrays sharded along that axis (or host arrays, which get sharded on entry).
    """

    def __init__(self, mesh: Mesh, axis: str = "dp"):
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self._cache: dict = {}

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def _sharded(self, x, spec):
        return jax.device_put(jnp.asarray(x), NamedSharding(self.mesh, spec))

    def allreduce(self, stacked, op: ReduceOp = ReduceOp.SUM):
        """stacked: array of shape (world_size, ...) — per-rank inputs on dim 0.
        Returns their elementwise reduction (shape ``stacked.shape[1:]``)."""
        stacked = jnp.asarray(stacked)
        if stacked.shape[0] != self.world_size:
            raise ValueError(
                f"dim 0 ({stacked.shape[0]}) must equal world_size ({self.world_size})"
            )
        key = ("allreduce", op)
        fn = self._cache.get(key)
        if fn is None:
            fn = jax.jit(
                shard_map(
                    partial(allreduce, axis_name=self.axis, op=op),
                    mesh=self.mesh,
                    in_specs=P(self.axis),
                    out_specs=P(None),
                )
            )
            # Keys are ("allreduce", <ReduceOp member>): bounded by the enum.
            self._cache[key] = fn  # raylint: disable=RL602 (key space is the fixed ReduceOp enum)
        return fn(self._sharded(stacked, P(self.axis)))[0]
