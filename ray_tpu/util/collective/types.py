"""Collective types/enums.

Design parity: reference `python/ray/util/collective/types.py` (Backend, ReduceOp, and
the option dataclasses passed to each verb). TPU-native split: the reference has one
backend tier (NCCL/gloo eager ops); here there are two — HOST (eager, DCN-class, via the
object store + a rendezvous actor; the gloo analog) and XLA (in-graph ICI collectives
emitted by the compiler inside jit/shard_map; see ray_tpu/util/collective/xla.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum


class Backend(str, Enum):
    """Which transport executes the collective."""

    HOST = "host"  # eager, CPU/host memory, rendezvous-actor coordinated (gloo analog)
    XLA = "xla"  # in-graph ICI/DCN collectives inside jit (NCCL analog, compiler-inserted)

    @classmethod
    def of(cls, value: "Backend | str") -> "Backend":
        if isinstance(value, Backend):
            return value
        v = str(value).lower()
        # Accept the reference's backend names so ported user code runs unchanged.
        if v in ("gloo", "torch_gloo", "host", "cpu"):
            return cls.HOST
        if v in ("nccl", "xla", "ici", "tpu"):
            return cls.XLA
        raise ValueError(f"unknown collective backend {value!r}")


class ReduceOp(str, Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"  # not in NCCL; natural on TPU (psum / axis_size), so first-class here


@dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class GroupInfo:
    group_name: str
    world_size: int
    rank: int
    backend: Backend = Backend.HOST
    extra: dict = field(default_factory=dict)
