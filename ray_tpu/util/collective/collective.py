"""ray_tpu.util.collective: eager collective communication among tasks/actors.

Design parity: reference `python/ray/util/collective/collective.py` —
`init_collective_group` (:180), declarative `create_collective_group` (:217),
`allreduce` (:325), `barrier` (:365), `reduce`/`broadcast`/`allgather`/`reducescatter`
(:378-597), p2p `send`/`recv` (:598-721), `GroupManager` (:75).

TPU-native shape: the `*_multigpu` variants of the reference are deliberately absent —
on TPU one process owns all local chips and collectives over them are in-graph XLA ops
(see `ray_tpu.util.collective.xla`), not per-device eager calls. The eager verbs here run
on the HOST backend (rendezvous-actor coordinated, DCN-class traffic).
"""

from __future__ import annotations

import threading

from ray_tpu.util.collective.collective_group.host_group import HostGroup
from ray_tpu.util.collective.types import (
    AllGatherOptions,
    AllReduceOptions,
    Backend,
    BarrierOptions,
    BroadcastOptions,
    GroupInfo,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)

_DECL_KV_NS = "collective_groups"


class GroupManager:
    """Process-local registry of collective groups this worker participates in."""

    def __init__(self):
        self._groups: dict[str, HostGroup] = {}
        self._lock = threading.Lock()

    def create_group(self, group_name: str, world_size: int, rank: int, backend) -> HostGroup:
        backend = Backend.of(backend)
        if backend != Backend.HOST:
            raise ValueError(
                "eager collective groups use the HOST backend; in-graph device "
                "collectives are expressed with ray_tpu.util.collective.xla inside "
                "jit/shard_map"
            )
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(f"collective group {group_name!r} already initialized")
            group = HostGroup(world_size, rank, group_name)
            self._groups[group_name] = group
            return group

    def get_group(self, group_name: str) -> HostGroup:
        with self._lock:
            group = self._groups.get(group_name)
        if group is None:
            group = self._maybe_init_declared(group_name)
        if group is None:
            raise RuntimeError(
                f"collective group {group_name!r} is not initialized in this worker; "
                "call init_collective_group() or create_collective_group() first"
            )
        return group

    def _maybe_init_declared(self, group_name: str):
        """Lazily join a group declared via create_collective_group: resolve this
        worker's rank from its actor id recorded in the GCS declaration."""
        import ray_tpu
        from ray_tpu._private import serialization
        from ray_tpu._private.worker import global_worker

        worker = global_worker()
        raw = worker.gcs_kv_get(_DECL_KV_NS, group_name.encode())
        if raw is None:
            return None
        decl = serialization.loads(raw)
        me = worker.actor_id
        if me is None or me.binary() not in decl["ranks"]:
            return None
        rank = decl["ranks"][me.binary()]
        return self.create_group(group_name, decl["world_size"], rank, decl["backend"])

    def is_initialized(self, group_name: str) -> bool:
        with self._lock:
            return group_name in self._groups

    def destroy_group(self, group_name: str):
        with self._lock:
            group = self._groups.pop(group_name, None)
        if group is not None:
            group.destroy_group()


_group_mgr = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend="host",
    group_name: str = "default",
) -> None:
    """Imperative init: every member calls this with its own rank (reference :180)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    _group_mgr.create_group(group_name, world_size, rank, backend)


def create_collective_group(
    actors,
    world_size: int,
    ranks: list[int],
    backend="host",
    group_name: str = "default",
) -> None:
    """Declarative init from the driver: assign ranks to actors; each actor joins
    lazily on its first collective call (reference :217)."""
    from ray_tpu._private import serialization
    from ray_tpu._private.worker import global_worker

    if len(actors) != len(ranks) or sorted(ranks) != list(range(world_size)):
        raise ValueError("ranks must be a permutation of range(world_size) matching actors")
    decl = {
        "world_size": world_size,
        "backend": str(Backend.of(backend).value),
        "ranks": {a._actor_id.binary(): r for a, r in zip(actors, ranks)},
    }
    global_worker().gcs_kv_put(_DECL_KV_NS, group_name.encode(), serialization.dumps(decl))


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.is_initialized(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down this member's group state, kill the coordinator, and delete any
    declarative registration so the name can be reused."""
    _group_mgr.destroy_group(group_name)
    try:
        from ray_tpu._private.worker import global_worker

        global_worker().gcs_call("kv_del", _DECL_KV_NS, group_name.encode())
    except Exception:
        pass


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).world_size


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).allreduce(tensor, AllReduceOptions(reduceOp=op))


def barrier(group_name: str = "default") -> None:
    _group_mgr.get_group(group_name).barrier(BarrierOptions())


def reduce(tensor, dst_rank: int = 0, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).reduce(
        tensor, ReduceOptions(reduceOp=op, root_rank=dst_rank)
    )


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group_mgr.get_group(group_name).broadcast(tensor, BroadcastOptions(root_rank=src_rank))


def broadcast_object(obj, src_rank: int = 0, group_name: str = "default"):
    return _group_mgr.get_group(group_name).broadcast_object(obj, src_rank)


def allgather(tensor, group_name: str = "default") -> list:
    return _group_mgr.get_group(group_name).allgather(tensor, AllGatherOptions())


def allgather_object(obj, group_name: str = "default") -> list:
    return _group_mgr.get_group(group_name).allgather_object(obj)


def reducescatter(tensor_list, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).reducescatter(
        tensor_list, ReduceScatterOptions(reduceOp=op)
    )


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    _group_mgr.get_group(group_name).send(tensor, SendOptions(dst_rank=dst_rank))


def recv(src_rank: int, group_name: str = "default"):
    return _group_mgr.get_group(group_name).recv(opts=RecvOptions(src_rank=src_rank))
