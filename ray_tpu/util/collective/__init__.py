"""ray_tpu.util.collective: two-tier collectives (eager HOST / in-graph XLA).

Reference parity: python/ray/util/collective/__init__.py.
"""

from ray_tpu.util.collective.collective import (  # noqa: F401
    allgather,
    allgather_object,
    allreduce,
    barrier,
    broadcast,
    broadcast_object,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.util.collective.types import Backend, ReduceOp  # noqa: F401
from ray_tpu.util.collective import xla  # noqa: F401
