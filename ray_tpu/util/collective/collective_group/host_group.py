"""Host-tier collective group: eager collectives over the object store + a rendezvous actor.

Design parity: reference `python/ray/util/collective/collective_group/nccl_collective_group.py`
(NCCLGroup :121) — but where NCCL rendezvouses a unique id through a named `Rendezvous`
actor (:29) and then moves tensors over GPU rings, the TPU-native host tier keeps both
the rendezvous AND the data on the control plane: a named async coordinator actor gathers
each member's contribution and hands back the reduced/gathered result. This is the right
tier for DCN-class, small/medium host tensors (model metadata, eval metrics, rank-0
broadcasts). Bulk device traffic belongs to the XLA tier (in-graph ICI collectives,
`ray_tpu/util/collective/xla.py`), which the compiler schedules — a split the NCCL design
doesn't have (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import numpy as np

from ray_tpu.util.collective.types import (
    AllGatherOptions,
    AllReduceOptions,
    Backend,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)
from ray_tpu.util.collective.collective_group.base import BaseGroup

_COORD_PREFIX = "ray_tpu_collective::"


def _reduce(values: list, op: ReduceOp):
    arrs = [np.asarray(v) for v in values]
    if op == ReduceOp.SUM:
        out = arrs[0].copy()
        for a in arrs[1:]:
            out = out + a
    elif op == ReduceOp.PRODUCT:
        out = arrs[0].copy()
        for a in arrs[1:]:
            out = out * a
    elif op == ReduceOp.MIN:
        out = np.minimum.reduce(arrs)
    elif op == ReduceOp.MAX:
        out = np.maximum.reduce(arrs)
    elif op == ReduceOp.MEAN:
        out = np.mean(np.stack(arrs), axis=0).astype(arrs[0].dtype)
    else:
        raise ValueError(f"unknown reduce op {op}")
    return out


class _Coordinator:
    """Named async actor that synchronizes one collective group.

    Each verb call is keyed by (verb, seq); contributions buffer until world_size have
    arrived, then every waiter is released with its rank's slice of the result.
    """

    def __init__(self, world_size: int):
        import asyncio
        import collections
        import time

        self._world_size = world_size
        self._ops = collections.defaultdict(
            lambda: {
                "contrib": {},
                "event": asyncio.Event(),
                "out": None,
                "visits": 0,
                "failed": False,
                "born": time.time(),
            }
        )
        self._p2p = {}
        self._p2p_events = collections.defaultdict(asyncio.Event)

    def world_size(self) -> int:
        return self._world_size

    def _leave(self, key, slot):
        """A rank is done with this op (result fetched, timed out, or aborted);
        free the slot once every rank has passed through."""
        slot["visits"] += 1
        if slot["visits"] >= self._world_size:
            self._ops.pop(key, None)

    def _gc_stale(self, ttl_s: float = 600.0):
        """Drop failed slots whose stragglers never showed up (bounded leak)."""
        import time

        now = time.time()
        for key in [
            k for k, s in self._ops.items() if s["failed"] and now - s["born"] > ttl_s
        ]:
            del self._ops[key]

    async def collect(self, verb: str, seq: int, rank: int, value, op, timeout_s: float):
        """Generic gather-compute-scatter: returns this rank's result for the op.

        Timeout consistency: the first waiter to time out marks the op failed and
        releases everyone — all ranks (including stragglers arriving later) raise, so
        no subset ever believes the collective succeeded.
        """
        import asyncio

        self._gc_stale()
        key = (verb, seq)
        slot = self._ops[key]
        if slot["failed"]:
            self._leave(key, slot)
            raise TimeoutError(
                f"collective {verb}#{seq} was aborted after a peer timed out"
            )
        slot["contrib"][rank] = value
        if len(slot["contrib"]) == self._world_size:
            ranked = [slot["contrib"][r] for r in range(self._world_size)]
            slot["out"] = self._compute(verb, ranked, op)
            slot["event"].set()
        else:
            try:
                await asyncio.wait_for(slot["event"].wait(), timeout_s)
            except asyncio.TimeoutError:
                missing = [r for r in range(self._world_size) if r not in slot["contrib"]]
                slot["failed"] = True
                slot["event"].set()
                self._leave(key, slot)
                raise TimeoutError(
                    f"collective {verb}#{seq} timed out after {timeout_s}s; "
                    f"missing ranks {missing}"
                ) from None
        if slot["failed"]:
            self._leave(key, slot)
            raise TimeoutError(
                f"collective {verb}#{seq} was aborted after a peer timed out"
            )
        out = slot["out"]
        self._leave(key, slot)
        if verb in ("reducescatter",):
            return out[rank]
        if verb == "reduce":
            root = op[1]
            return out if rank == root else None
        return out

    def _compute(self, verb: str, ranked: list, op):
        if verb == "barrier":
            return True
        if verb == "allreduce":
            return _reduce(ranked, op)
        if verb == "reduce":
            return _reduce(ranked, op[0])
        if verb == "broadcast":
            return ranked[op]  # op = root rank
        if verb == "allgather":
            return [np.asarray(v) for v in ranked]
        if verb == "reducescatter":
            # Each rank contributes a list of world_size chunks; rank r gets the
            # reduction of everyone's chunk r.
            return [
                _reduce([ranked[src][r] for src in range(self._world_size)], op)
                for r in range(self._world_size)
            ]
        raise ValueError(f"unknown verb {verb}")

    async def p2p_send(self, src: int, dst: int, seq: int, value):
        key = (src, dst, seq)
        self._p2p[key] = value
        self._p2p_events[key].set()
        return True

    async def p2p_recv(self, src: int, dst: int, seq: int, timeout_s: float):
        import asyncio

        key = (src, dst, seq)
        try:
            await asyncio.wait_for(self._p2p_events[key].wait(), timeout_s)
        except asyncio.TimeoutError:
            raise TimeoutError(f"recv from rank {src} (op {seq}) timed out") from None
        value = self._p2p.pop(key)
        del self._p2p_events[key]
        return value


def _get_coordinator(group_name: str, world_size: int):
    import ray_tpu

    actor_cls = ray_tpu.remote(_Coordinator)
    return actor_cls.options(
        name=_COORD_PREFIX + group_name,
        get_if_exists=True,
        num_cpus=0,
        max_concurrency=max(world_size * 4, 16),
    ).remote(world_size)


class HostGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        import ray_tpu

        super().__init__(world_size, rank, group_name)
        self._coordinator = _get_coordinator(group_name, world_size)
        # A stale coordinator from a destroyed-but-leaked or re-sized group would
        # silently desync every op; fail loudly instead.
        actual = ray_tpu.get(self._coordinator.world_size.remote())
        if actual != world_size:
            raise RuntimeError(
                f"collective group {group_name!r} already exists with "
                f"world_size={actual} (asked for {world_size}); destroy it first "
                "with destroy_collective_group()"
            )
        self._seq = 0
        self._p2p_seq: dict = {}

    @classmethod
    def backend(cls):
        return Backend.HOST

    def _next(self) -> int:
        self._seq += 1
        return self._seq

    def _call(self, verb, value, op, timeout_ms):
        import ray_tpu

        ref = self._coordinator.collect.remote(
            verb, self._next(), self._rank, value, op, timeout_ms / 1000.0
        )
        return ray_tpu.get(ref, timeout=timeout_ms / 1000.0 + 30)

    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        return self._call("allreduce", np.asarray(tensor), opts.reduceOp, opts.timeout_ms)

    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        self._call("barrier", None, None, opts.timeout_ms)

    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        return self._call(
            "reduce", np.asarray(tensor), (opts.reduceOp, opts.root_rank), opts.timeout_ms
        )

    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        value = np.asarray(tensor) if tensor is not None else None
        return self._call("broadcast", value, opts.root_rank, opts.timeout_ms)

    def broadcast_object(self, obj, root_rank: int = 0, timeout_ms: int = 30000):
        """Broadcast an arbitrary picklable object (reference gloo's bcast-object path)."""
        return self._call("broadcast", obj, root_rank, timeout_ms)

    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()):
        return self._call("allgather", np.asarray(tensor), None, opts.timeout_ms)

    def allgather_object(self, obj, timeout_ms: int = 30000):
        return self._call("allgather", obj, None, timeout_ms)

    def reducescatter(self, tensor_list, opts: ReduceScatterOptions = ReduceScatterOptions()):
        chunks = [np.asarray(t) for t in tensor_list]
        if len(chunks) != self._world_size:
            raise ValueError(
                f"reducescatter needs {self._world_size} chunks, got {len(chunks)}"
            )
        return self._call("reducescatter", chunks, opts.reduceOp, opts.timeout_ms)

    def send(self, tensor, opts: SendOptions):
        import ray_tpu

        key = (self._rank, opts.dst_rank)
        seq = self._p2p_seq.get(key, 0) + 1
        self._p2p_seq[key] = seq
        ray_tpu.get(
            self._coordinator.p2p_send.remote(self._rank, opts.dst_rank, seq, np.asarray(tensor))
        )

    def recv(self, shape=None, dtype=None, opts: RecvOptions = RecvOptions()):
        import ray_tpu

        key = (opts.src_rank, self._rank)
        seq = self._p2p_seq.get(key, 0) + 1
        self._p2p_seq[key] = seq
        value = ray_tpu.get(
            self._coordinator.p2p_recv.remote(
                opts.src_rank, self._rank, seq, opts.timeout_ms / 1000.0
            ),
            timeout=opts.timeout_ms / 1000.0 + 30,
        )
        return value

    def destroy_group(self):
        """Kill the named coordinator so the group name can be re-created (possibly
        with a different world_size). Idempotent across members."""
        import ray_tpu

        coordinator, self._coordinator = self._coordinator, None
        if coordinator is not None:
            try:
                ray_tpu.kill(coordinator)
            except Exception:
                pass  # another member already killed it
