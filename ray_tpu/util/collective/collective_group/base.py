"""BaseGroup: the interface every collective backend implements.

Design parity: reference `python/ray/util/collective/collective_group/base_collective_group.py`
(BaseGroup ABC with rank/world_size/group_name and the verb methods NCCLGroup/GlooGroup
implement).
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from ray_tpu.util.collective.types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def group_name(self) -> str:
        return self._group_name

    def destroy_group(self):
        pass

    @classmethod
    @abstractmethod
    def backend(cls):
        ...

    @abstractmethod
    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        ...

    @abstractmethod
    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        ...

    @abstractmethod
    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        ...

    @abstractmethod
    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        ...

    @abstractmethod
    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()):
        ...

    @abstractmethod
    def reducescatter(self, tensor, opts: ReduceScatterOptions = ReduceScatterOptions()):
        ...

    @abstractmethod
    def send(self, tensor, opts: SendOptions):
        ...

    @abstractmethod
    def recv(self, shape, dtype, opts: RecvOptions):
        ...
