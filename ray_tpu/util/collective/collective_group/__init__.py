from ray_tpu.util.collective.collective_group.base import BaseGroup  # noqa: F401
from ray_tpu.util.collective.collective_group.host_group import HostGroup  # noqa: F401
