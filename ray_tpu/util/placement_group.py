"""Placement groups: atomic gang reservation of resource bundles across nodes.

Design parity: reference `python/ray/util/placement_group.py` (:146 placement_group) +
GCS-side scheduling (`src/ray/gcs/gcs_placement_group_manager.h`). Strategies: PACK,
SPREAD, STRICT_PACK, STRICT_SPREAD. On TPU clusters a slice is reserved atomically via a
STRICT_PACK bundle over the slice-head resource (see accelerators/tpu.py).
"""

from __future__ import annotations

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.worker import global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict]):
        self.id = pg_id
        self.bundles = bundles

    def ready(self, timeout: float = 60.0) -> bool:
        info = global_worker().gcs_call("pg_wait_ready", self.id, timeout)
        return info["state"] == "ALIVE"

    def wait(self, timeout_seconds: float = 60.0) -> bool:
        return self.ready(timeout_seconds)

    @property
    def bundle_specs(self) -> list[dict]:
        return self.bundles

    def allocations(self):
        info = global_worker().gcs_call("pg_wait_ready", self.id, 0.1)
        return info["allocations"]

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


def placement_group(
    bundles: list[dict],
    strategy: str = "PACK",
    name: str = "",
    lifetime: str | None = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty resource dicts")
    pg_id = PlacementGroupID.from_random()
    global_worker().gcs_call("create_placement_group", pg_id, bundles, strategy, name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    global_worker().gcs_call("remove_placement_group", pg.id)


def placement_group_table() -> list:
    return global_worker().gcs_call("list_placement_groups")
