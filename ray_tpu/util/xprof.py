"""Compute-plane observatory: XLA program registry, device-memory ledger,
and on-demand profiler capture (docs/observability.md "compute plane").

Three pieces, all host-side and pull-free:

- **ProgramRegistry** — a per-process registry every ``_program``-style jit
  cache hooks into (DecodeEngine prefill/decode/verify/install, Learner
  update, checkpoint restore).  Each compiled program gets one entry keyed
  ``(owner, key)`` recording compile wall time, invocation counts, and a
  cumulative execution estimate.  A process-wide ``xla_recompiles_total``
  counter distinguishes warmup compiles (first compile of a key) from
  post-warmup retrace storms (any later compile of an already-seen key) —
  the runtime complement to jaxlint RL602/RL604.
- **Device-memory ledger** — components register a callable returning their
  byte accounting; ``device_memory_report()`` joins every owner with the
  raw ``device.memory_stats()`` the backend provides (TPU/GPU only — the
  CPU backend returns nothing and the report says so instead of guessing).
  ``oom_snapshot()`` ranks owners by bytes for RESOURCE_EXHAUSTED
  forensics.
- **ProfilerCapture** — ``start_capture()`` / ``stop_capture()`` around
  ``jax.profiler`` trace capture, leaksan-tracked (kind
  ``profiler_capture``) and leaklint-paired so an abandoned capture cannot
  pin trace buffers forever.  ``capture(duration_s)`` is the one-shot
  helper the actor surfaces expose to ``util.state.capture_profile``.

Flush rule (PR 9/11/13): nothing here touches ``util.metrics`` on the hot
path.  Registry mutation is plain-int arithmetic; metric objects are
created lazily and updated only inside ``report()`` /
``device_memory_report()``, which are called exclusively from
``scheduler_stats()``-style report paths.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "ProgramRegistry",
    "ProfilerCapture",
    "capture",
    "device_memory_report",
    "is_resource_exhausted",
    "oom_snapshot",
    "register_memory_owner",
    "registry",
    "start_capture",
    "stop_capture",
    "unregister_memory_owner",
]

# Backstop on registry size: well past any sane program count (the engine
# caps its own caches at llm_max_jit_programs); oldest entries evicted.
_MAX_ENTRIES = 4096


class _InstrumentedProgram:
    """A compiled-program wrapper that times its first call (jax compiles
    synchronously on first invocation: trace + lower + compile happen
    inline, only execution is async) and counts every later one.  Attribute
    access falls through to the underlying jit object so callers that poke
    ``_cache_size()`` etc. keep working.  Adds zero device syncs."""

    __slots__ = ("_fn", "_entry", "_registry", "_compiled")

    def __init__(self, fn, entry, reg):
        self._fn = fn
        self._entry = entry
        self._registry = reg
        self._compiled = False

    def __call__(self, *args, **kwargs):
        if self._compiled:
            self._entry["invocations"] += 1  # GIL-cheap; no lock, no sync
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        self._registry._note_compiled(self._entry, time.perf_counter() - t0)
        self._compiled = True
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)

    @property
    def __wrapped__(self):
        return self._fn


class ProgramRegistry:
    """Per-process registry of compiled XLA programs, keyed (owner, key)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, Any], dict] = {}
        self._recompiles_total = 0
        self._compiles_total = 0
        # metric-export watermarks: counters are exported as deltas from the
        # report path only, never from the mutation path
        self._exported = {"compiles": 0, "recompiles": 0}
        self._metrics: Dict[str, Any] = {}

    # -- registration --------------------------------------------------------

    def instrument(self, owner: str, key: Any, fn) -> _InstrumentedProgram:
        """Wrap a freshly built (uncompiled) jit program.  Re-instrumenting
        an already-seen (owner, key) — an eviction rebuild or a retrace —
        marks the next compile as a *recompile*, not warmup."""
        entry = self._entry(owner, key)
        return _InstrumentedProgram(fn, entry, self)

    def _entry(self, owner: str, key: Any) -> dict:
        rkey = (owner, _freeze(key))
        with self._lock:
            entry = self._entries.get(rkey)
            if entry is None:
                if len(self._entries) >= _MAX_ENTRIES:
                    self._entries.pop(next(iter(self._entries)))
                entry = self._entries[rkey] = {
                    "owner": owner,
                    "key": rkey[1],
                    "compiles": 0,
                    "recompiles": 0,
                    "invocations": 0,
                    "compile_s": 0.0,
                    "last_compile_s": 0.0,
                    "exec_s": 0.0,
                }
            return entry

    def _note_compiled(self, entry: dict, seconds: float) -> None:
        with self._lock:
            first = entry["compiles"] == 0
            entry["compiles"] += 1
            entry["invocations"] += 1
            entry["compile_s"] += seconds
            entry["last_compile_s"] = seconds
            self._compiles_total += 1
            if not first:
                entry["recompiles"] += 1
                self._recompiles_total += 1

    # -- call-site hooks (for programs not built through instrument()) ------

    def note_exec(self, owner: str, key: Any, seconds: float) -> None:
        """Record measured execution time at a call site that already pays
        a host sync (e.g. Learner.update after its device_get)."""
        entry = self._entry(owner, key)
        entry["exec_s"] += seconds

    def note_span(self, owner: str, key: Any, seconds: float) -> None:
        """Record a one-shot compute span (checkpoint restore): invocation
        plus wall time, with no compile accounting — restores build fresh
        programs by design and must never read as a retrace storm."""
        entry = self._entry(owner, key)
        entry["invocations"] += 1
        entry["exec_s"] += seconds

    # -- report path ---------------------------------------------------------

    @property
    def recompiles_total(self) -> int:
        return self._recompiles_total

    def report(self, owner: Optional[str] = None) -> dict:
        """Per-program rows plus process totals.  Report-path only: this is
        also where the metric objects are updated (flush rule)."""
        with self._lock:
            rows = [
                dict(e) for e in self._entries.values()
                if owner is None or e["owner"] == owner
            ]
            totals = {
                "programs": len(self._entries),
                "compiles_total": self._compiles_total,
                "recompiles_total": self._recompiles_total,
                "compile_s_total": sum(
                    e["compile_s"] for e in self._entries.values()),
            }
            compile_delta = self._compiles_total - self._exported["compiles"]
            recompile_delta = (
                self._recompiles_total - self._exported["recompiles"])
            self._exported["compiles"] = self._compiles_total
            self._exported["recompiles"] = self._recompiles_total
        rows.sort(key=lambda e: (-e["compiles"], -e["invocations"]))
        self._emit_metrics(totals, compile_delta, recompile_delta)
        return {"programs": rows, "totals": totals}

    def forget_owner(self, owner: str) -> None:
        with self._lock:
            for rkey in [k for k in self._entries if k[0] == owner]:
                del self._entries[rkey]

    def _emit_metrics(self, totals, compile_delta, recompile_delta) -> None:
        try:
            from ray_tpu.util import metrics as m

            mm = self._metrics
            if not mm:
                mm["programs"] = m.Gauge(
                    "xla_programs_registered",
                    "compiled XLA programs known to the registry")
                mm["compiles"] = m.Counter(
                    "xla_compiles_total", "XLA program compilations")
                mm["recompiles"] = m.Counter(
                    "xla_recompiles_total",
                    "post-warmup recompilations of an already-seen program "
                    "key (retrace storms; runtime RL602/RL604 complement)")
            mm["programs"].set(totals["programs"])
            if compile_delta:
                mm["compiles"].inc(compile_delta)
            if recompile_delta:
                mm["recompiles"].inc(recompile_delta)
            # report() IS the flush point (the PR 9/11/13 rule): force the
            # export so a scrape right after a stats call sees fresh counters.
            for metric in mm.values():
                metric.flush()
        except Exception:
            pass  # metrics plane unavailable (no ray runtime): report still works

    def reset(self) -> None:
        """Test hook: drop every entry and counter."""
        with self._lock:
            self._entries.clear()
            self._recompiles_total = 0
            self._compiles_total = 0
            self._exported = {"compiles": 0, "recompiles": 0}


def _freeze(key):
    if isinstance(key, list):
        return tuple(_freeze(k) for k in key)
    if isinstance(key, tuple):
        return tuple(_freeze(k) for k in key)
    return key


_REGISTRY = ProgramRegistry()


def registry() -> ProgramRegistry:
    """The per-process program registry singleton."""
    return _REGISTRY


# ---------------------------------------------------------------------------
# Device-memory ledger
# ---------------------------------------------------------------------------

_MEM_LOCK = threading.Lock()
_MEM_OWNERS: Dict[str, Callable[[], dict]] = {}
_MEM_METRICS: Dict[str, Any] = {}
#: own lock so metric creation/set never holds _MEM_LOCK through a flush RPC
_MEM_METRICS_LOCK = threading.Lock()


def register_memory_owner(name: str, fn: Callable[[], dict]) -> None:
    """Register a byte-accounting callable under ``name``.  ``fn`` returns
    ``{"bytes": int}`` at minimum; optional ``"per_device": {dev: bytes}``
    and ``"host_bytes": int`` refine the attribution.  It is called from
    report paths only and must not touch device state (shape metadata is
    fine; ``device_get`` is not)."""
    with _MEM_LOCK:
        _MEM_OWNERS[name] = fn


def unregister_memory_owner(name: str) -> None:
    with _MEM_LOCK:
        _MEM_OWNERS.pop(name, None)


def device_memory_report() -> dict:
    """One per-device view of framework-attributed bytes by owner plus raw
    backend ``memory_stats()`` (peak/in-use) where available.  Report-path
    only (also updates the ledger gauges)."""
    with _MEM_LOCK:
        owners = dict(_MEM_OWNERS)
    out_owners: Dict[str, dict] = {}
    per_device: Dict[str, int] = {}
    tracked_total = 0
    for name, fn in sorted(owners.items()):
        try:
            row = dict(fn() or {})
        except Exception as exc:  # a dead owner must not kill the report
            out_owners[name] = {"error": repr(exc)}
            continue
        row.setdefault("bytes", 0)
        tracked_total += int(row["bytes"])
        for dev, nbytes in (row.get("per_device") or {}).items():
            per_device[str(dev)] = per_device.get(str(dev), 0) + int(nbytes)
        out_owners[name] = row
    devices: List[dict] = []
    try:
        import jax

        for d in jax.devices():
            dev = {"id": d.id, "platform": d.platform,
                   "kind": getattr(d, "device_kind", "")}
            try:
                stats = d.memory_stats()  # CPU backend: raises/None
            except Exception:
                stats = None
            if stats:
                dev["memory_stats"] = {
                    k: stats[k] for k in
                    ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                    if k in stats
                }
            devices.append(dev)
    except Exception:
        pass
    report = {
        "owners": out_owners,
        "tracked_bytes_total": tracked_total,
        "per_device_tracked_bytes": per_device,
        "devices": devices,
    }
    _emit_mem_metrics(out_owners, tracked_total)
    return report


def _emit_mem_metrics(owners: Dict[str, dict], total: int) -> None:
    try:
        from ray_tpu.util import metrics as m

        with _MEM_METRICS_LOCK:
            if not _MEM_METRICS:
                _MEM_METRICS["owner"] = m.Gauge(
                    "device_mem_owner_bytes",
                    "framework-attributed device bytes by owner",
                    tag_keys=("owner",))
                _MEM_METRICS["total"] = m.Gauge(
                    "device_mem_tracked_bytes",
                    "framework-attributed device bytes, all owners")
            metrics = dict(_MEM_METRICS)
        for name, row in owners.items():
            if "bytes" in row:
                metrics["owner"].set(row["bytes"], tags={"owner": name})
        metrics["total"].set(total)
        for metric in metrics.values():
            metric.flush()
    except Exception:
        pass


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when an exception looks like a device-memory exhaustion escape
    (XLA surfaces these as RESOURCE_EXHAUSTED / out-of-memory strings on
    every backend; there is no stable exception type to catch)."""
    text = f"{type(exc).__name__}: {exc}"
    low = text.lower()
    return ("resource_exhausted" in low or "resource exhausted" in low
            or "out of memory" in low or "out_of_memory" in low)


def oom_snapshot() -> dict:
    """The ledger ranked by bytes descending — what the flight recorder
    attaches to an OOM before the engine re-raises."""
    report = device_memory_report()
    ranked = sorted(
        ((name, row.get("bytes", 0)) for name, row in report["owners"].items()
         if "error" not in row),
        key=lambda kv: -kv[1])
    return {
        "ts": time.time(),
        "ranked_owners": [{"owner": n, "bytes": b} for n, b in ranked],
        "tracked_bytes_total": report["tracked_bytes_total"],
        "devices": report["devices"],
    }


# ---------------------------------------------------------------------------
# Profiler capture
# ---------------------------------------------------------------------------

_CAPTURE_LOCK = threading.Lock()
_ACTIVE_CAPTURE: Optional["ProfilerCapture"] = None

# per-file / per-capture caps when shipping trace artifacts across actors
_MAX_FILE_BYTES = 4 << 20
_MAX_CAPTURE_BYTES = 32 << 20


class ProfilerCapture:
    """A single in-flight ``jax.profiler`` trace capture.  Acquire with
    ``start_capture()``; release with ``stop_capture()`` (or ``close()``,
    the abandon path) — leaklint pairs them (RL801) and leaksan tracks the
    live handle under kind ``profiler_capture``."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self.started_at = time.time()
        self.backend_trace = False
        self._stopped = False
        try:
            import jax

            jax.profiler.start_trace(log_dir)
            self.backend_trace = True
        except Exception:
            # backend without a profiler (or a capture already running
            # outside us): the manifest records the miss, artifacts still
            # round-trip so the fleet path stays testable everywhere
            self.backend_trace = False
        from ray_tpu.devtools import leaksan

        leaksan.track("profiler_capture", self, detail=log_dir)

    def stop_capture(self) -> dict:
        """Stop the trace and write ``capture_manifest.json`` into the log
        dir; idempotent.  Returns the manifest."""
        if self._stopped:
            return self._manifest()
        self._stopped = True
        if self.backend_trace:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
        manifest = self._manifest()
        try:
            os.makedirs(self.log_dir, exist_ok=True)
            path = os.path.join(self.log_dir, "capture_manifest.json")
            with open(path, "w") as f:
                json.dump(manifest, f, indent=1)
        except OSError:
            pass
        from ray_tpu.devtools import leaksan

        leaksan.untrack("profiler_capture", self)
        global _ACTIVE_CAPTURE
        with _CAPTURE_LOCK:
            if _ACTIVE_CAPTURE is self:
                _ACTIVE_CAPTURE = None
        return manifest

    def close(self) -> dict:
        return self.stop_capture()

    def _manifest(self) -> dict:
        return {
            "log_dir": self.log_dir,
            "started_at": self.started_at,
            "duration_s": time.time() - self.started_at,
            "backend_trace": self.backend_trace,
            "pid": os.getpid(),
        }


def start_capture(log_dir: Optional[str] = None) -> ProfilerCapture:
    """Start a trace capture (one per process at a time).  The returned
    handle must be released via ``stop_capture()``/``close()``."""
    global _ACTIVE_CAPTURE
    with _CAPTURE_LOCK:
        if _ACTIVE_CAPTURE is not None:
            raise RuntimeError(
                f"profiler capture already active: {_ACTIVE_CAPTURE.log_dir}")
        if log_dir is None:
            log_dir = tempfile.mkdtemp(prefix="ray_tpu_xprof_")
        cap = ProfilerCapture(log_dir)
        _ACTIVE_CAPTURE = cap
        return cap


def stop_capture() -> Optional[dict]:
    """Stop the process's active capture, if any (module-level convenience
    for operator consoles; the handle's own method is the canonical path)."""
    with _CAPTURE_LOCK:
        cap = _ACTIVE_CAPTURE
    return cap.stop_capture() if cap is not None else None


def capture(duration_s: float = 3.0, log_dir: Optional[str] = None) -> dict:
    """One-shot capture: start, run for ``duration_s``, stop, and return the
    trace artifacts inline (size-capped) so an actor caller can gather them
    to the driver without a shared filesystem."""
    cap = start_capture(log_dir)
    trace_dir = cap.log_dir
    try:
        time.sleep(duration_s)
    finally:
        manifest = cap.stop_capture()
    files: Dict[str, bytes] = {}
    truncated: List[str] = []
    budget = _MAX_CAPTURE_BYTES
    for root, _dirs, names in os.walk(trace_dir):
        for name in sorted(names):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, trace_dir)
            try:
                size = os.path.getsize(path)
                if size > _MAX_FILE_BYTES or size > budget:
                    truncated.append(rel)
                    continue
                with open(path, "rb") as f:
                    files[rel] = f.read()
                budget -= size
            except OSError:
                truncated.append(rel)
    return {"log_dir": trace_dir, "manifest": manifest,
            "files": files, "truncated": truncated}
