"""Distributed tracing: span context propagated through task submission.

Design parity: reference `python/ray/util/tracing/tracing_helper.py` — opt-in
tracing that wraps remote calls in spans and propagates the context inside task
metadata (lazy/optional exporter). Here spans ride the existing task-event
pipeline (worker event buffer -> GCS -> `ray_tpu.timeline()` Chrome trace), so a
trace is reconstructable without any external collector: every event of a traced
call carries (trace_id, span_id, parent_span_id). Enable with
`tracing.enable()` or RAY_TPU_TRACING=1.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import uuid
from typing import Optional

_flag = {"enabled": None}
_ctx: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None
)


def enabled() -> bool:
    if _flag["enabled"] is not None:
        return _flag["enabled"]
    return os.environ.get("RAY_TPU_TRACING", "0").lower() in ("1", "true", "on")


def enable():
    _flag["enabled"] = True


def disable():
    _flag["enabled"] = False


def current() -> Optional[dict]:
    """The active span context {trace_id, span_id} (or None)."""
    return _ctx.get()


@contextlib.contextmanager
def trace(name: str = "root"):
    """Open a root span: every remote call made inside carries this trace."""
    ctx = {
        "trace_id": uuid.uuid4().hex,
        "span_id": uuid.uuid4().hex[:16],
        "name": name,
    }
    token = _ctx.set(ctx)
    try:
        yield ctx
    finally:
        _ctx.reset(token)


def propagation_context() -> Optional[dict]:
    """Context to embed in an outgoing task spec: a fresh child span under the
    caller's active span. An ACTIVE span always propagates — a worker executing
    a traced task forwards the trace to nested calls even if tracing was never
    enabled in that worker process (reference: context rides task metadata)."""
    parent = _ctx.get()
    if parent is None:
        if not enabled():
            return None
        parent = {"trace_id": uuid.uuid4().hex, "span_id": None}
    return {
        "trace_id": parent["trace_id"],
        "parent_span_id": parent.get("span_id"),
        "span_id": uuid.uuid4().hex[:16],
    }


def activate(trace_ctx: Optional[dict]):
    """Executor side: adopt the caller's span for the duration of the task."""
    if trace_ctx is None:
        return None
    return _ctx.set(
        {"trace_id": trace_ctx["trace_id"], "span_id": trace_ctx["span_id"]}
    )


def deactivate(token):
    if token is not None:
        _ctx.reset(token)


def event_fields(trace_ctx: Optional[dict]) -> dict:
    if not trace_ctx:
        return {}
    return {
        "trace_id": trace_ctx.get("trace_id"),
        "span_id": trace_ctx.get("span_id"),
        "parent_span_id": trace_ctx.get("parent_span_id"),
    }
