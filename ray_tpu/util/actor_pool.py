"""ActorPool: load-balance work over a fixed set of actors.

Parity: reference `python/ray/util/actor_pool.py` — submit/get_next/
get_next_unordered/map/map_unordered/has_next/push/pop_idle.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in SUBMISSION order. On timeout the future stays pending
        (retry later); on task error the actor is still returned to the pool."""
        if self._next_return_index not in self._index_to_future:
            raise StopIteration("no pending results")
        ref = self._index_to_future[self._next_return_index]
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._return_actor(self._future_to_actor.pop(ref))
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in COMPLETION order; same actor-return guarantees."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        # keep ordered bookkeeping consistent
        for idx, fut in list(self._index_to_future.items()):
            if fut is ref or fut == ref:
                del self._index_to_future[idx]
                break
        self._return_actor(self._future_to_actor.pop(ref))
        return ray_tpu.get(ref)

    def map(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor):
        self._return_actor(actor)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
