"""Serializability debugging: find WHICH member of an object fails to pickle.

Parity: reference `python/ray/util/check_serialize.py`
(inspect_serializability) — walks closures/attributes of a failing object and
reports the leaf culprits instead of one opaque pickling error.
"""

from __future__ import annotations

from typing import Any, Set, Tuple

import cloudpickle


def _try(obj) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def inspect_serializability(obj: Any, name: str = None, _depth: int = 3,
                            _seen: Set[int] = None, _prefix: str = "") -> Tuple[bool, list]:
    """Returns (serializable, [failure descriptions])."""
    name = name or getattr(obj, "__name__", type(obj).__name__)
    _seen = _seen if _seen is not None else set()
    if id(obj) in _seen:
        return True, []
    _seen.add(id(obj))
    if _try(obj):
        return True, []
    failures = []
    label = f"{_prefix}{name}"
    found_inner = False
    if _depth > 0:
        # closure cells of functions
        closure = getattr(obj, "__closure__", None)
        if closure:
            names = obj.__code__.co_freevars
            for var, cell in zip(names, closure):
                try:
                    inner = cell.cell_contents
                except ValueError:
                    continue
                ok, inner_fail = inspect_serializability(
                    inner, var, _depth - 1, _seen, label + ".")
                if not ok:
                    found_inner = True
                    failures.extend(inner_fail)
        # instance attributes
        attrs = getattr(obj, "__dict__", None)
        if isinstance(attrs, dict):
            for attr, value in attrs.items():
                ok, inner_fail = inspect_serializability(
                    value, attr, _depth - 1, _seen, label + ".")
                if not ok:
                    found_inner = True
                    failures.extend(inner_fail)
        # container elements
        if isinstance(obj, (list, tuple, set)):
            for i, v in enumerate(obj):
                ok, inner_fail = inspect_serializability(
                    v, f"[{i}]", _depth - 1, _seen, label)
                if not ok:
                    found_inner = True
                    failures.extend(inner_fail)
        elif isinstance(obj, dict):
            for k, v in obj.items():
                ok, inner_fail = inspect_serializability(
                    v, f"[{k!r}]", _depth - 1, _seen, label)
                if not ok:
                    found_inner = True
                    failures.extend(inner_fail)
    if not found_inner:
        failures.append(f"{label} (type {type(obj).__name__}) is not serializable")
    return False, failures
