"""Cross-version jax shims (the PR-2 shard_map compat, now shared).

jax moved `shard_map` from `jax.experimental.shard_map` to the top level and
renamed the manual-axes parameter (`auto={...}` complement on 0.4.x,
`axis_names={...}` on >= 0.8); `jax.lax.axis_size` is also absent on 0.4.x.
Every mesh-collective call site routes through here so the library (and its
tests) runs against either API surface unchanged.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.8 top-level; fall back to the experimental location
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None):
    """shard_map across jax versions.

    `axis_names=None` means fully manual over every mesh axis — the one
    spelling both API generations accept. With a manual-axes SUBSET, newer
    jax spells it `axis_names={...}`; 0.4.x spells the complement
    `auto={...}` (and type-checks replication of the manually-psummed
    outputs too eagerly, hence check_rep=False).
    """
    if axis_names is None:
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, axis_names=axis_names)
    except TypeError:
        auto = frozenset(mesh.axis_names) - set(axis_names)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, auto=auto)


def axis_size(axis_name) -> int:
    """`jax.lax.axis_size` (>= 0.6), or the psum-of-ones equivalent on 0.4.x."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:  # pragma: no cover - depends on installed jax
        return jax.lax.psum(1, axis_name)
