"""Distributed FIFO queue backed by an async actor.

Parity: reference `python/ray/util/queue.py` — Queue with put/get (blocking with
timeout), qsize/empty/full, put_nowait/get_nowait, batch variants.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            if timeout is None:
                return (True, await self._q.get())
            return (True, await asyncio.wait_for(self._q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    async def put_nowait(self, item):
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return (True, self._q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    async def put_nowait_batch(self, items: List[Any]):
        # Atomic: reject the whole batch if it cannot fit (no partial inserts).
        if self._q.maxsize and self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for item in items:
            self._q.put_nowait(item)
        return True

    async def get_nowait_batch(self, num_items: int):
        # Atomic: reject if fewer than num_items present (no partial pops).
        if self._q.qsize() < num_items:
            return (False, None)
        return (True, [self._q.get_nowait() for _ in range(num_items)])

    async def qsize(self):
        return self._q.qsize()

    async def empty(self):
        return self._q.empty()

    async def full(self):
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        opts = {"num_cpus": 0, **(actor_options or {})}
        self._actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        if not block:
            return self.put_nowait(item)
        ok = ray_tpu.get(self._actor.put.remote(item, timeout))
        if not ok:
            raise Full("queue put timed out")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            return self.get_nowait()
        ok, item = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty("queue get timed out")
        return item

    def put_nowait(self, item: Any):
        if not ray_tpu.get(self._actor.put_nowait.remote(item)):
            raise Full("queue is full")

    def get_nowait(self) -> Any:
        ok, item = ray_tpu.get(self._actor.get_nowait.remote())
        if not ok:
            raise Empty("queue is empty")
        return item

    def put_nowait_batch(self, items: List[Any]):
        if not ray_tpu.get(self._actor.put_nowait_batch.remote(list(items))):
            raise Full(f"batch of {len(items)} does not fit")

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, items = ray_tpu.get(self._actor.get_nowait_batch.remote(num_items))
        if not ok:
            raise Empty(f"fewer than {num_items} items in queue")
        return items

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self._actor.full.remote())

    def shutdown(self):
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass
