"""Job submission: run driver scripts on the cluster, supervised and observable.

Design parity: reference `python/ray/dashboard/modules/job/` — `JobSubmissionClient`
(sdk.py:36) + the job manager/supervisor pattern (`job_manager.py`,
`job_supervisor.py`: the entrypoint runs as a subprocess under a supervisor actor;
status and logs are recorded centrally). Here status lives in the GCS KV store and
logs in a per-job file the supervisor tails back.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

_NS = "job"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class _JobSupervisor:
    """Async actor: runs one job's entrypoint as a subprocess and records state."""

    def __init__(self, job_id: str, entrypoint: str, env: dict, cwd: Optional[str]):
        self._job_id = job_id
        self._entrypoint = entrypoint
        self._env = env
        self._cwd = cwd
        self._proc = None
        self._log_path = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"rtpu_job_{job_id}.log"
        )

    def _put_status(self, status: str, message: str = ""):
        import ray_tpu

        worker = ray_tpu.global_worker()
        payload = {
            "job_id": self._job_id,
            "status": status,
            "entrypoint": self._entrypoint,
            "message": message,
            "log_path": self._log_path,
            "updated_at": time.time(),
        }
        worker.gcs_call("kv_put", _NS, self._job_id.encode(),
                        json.dumps(payload).encode(), True)

    async def run(self) -> str:
        import asyncio
        import subprocess

        import ray_tpu

        worker = ray_tpu.global_worker()
        env = dict(os.environ)
        env.update(self._env)
        # The entrypoint attaches to THIS cluster as a driver.
        gcs_host, gcs_port = worker.gcs_addr
        env["RAY_TPU_ADDRESS"] = f"{gcs_host}:{gcs_port}"
        env["RAY_TPU_RAYLET_PORT"] = str(worker.raylet_addr[1])
        self._put_status(JobStatus.RUNNING)
        loop = asyncio.get_running_loop()

        def run_proc():
            with open(self._log_path, "wb") as log:
                self._proc = subprocess.Popen(
                    self._entrypoint, shell=True, stdout=log, stderr=log,
                    env=env, cwd=self._cwd,
                )
                return self._proc.wait()

        code = await loop.run_in_executor(None, run_proc)
        if code == 0:
            self._put_status(JobStatus.SUCCEEDED)
            return JobStatus.SUCCEEDED
        status = JobStatus.STOPPED if code in (-15, -9) else JobStatus.FAILED
        self._put_status(status, f"exit code {code}")
        return status

    async def stop(self) -> bool:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            return True
        return False

    async def logs(self, tail_bytes: int = 65536) -> str:
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""


class JobSubmissionClient:
    """Parity: reference JobSubmissionClient(address).submit_job(entrypoint=...)."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, ignore_reinit_error=True)
        self._worker = ray_tpu.global_worker()

    @classmethod
    def _attached(cls) -> "JobSubmissionClient":
        return cls()

    def submit_job(
        self,
        *,
        entrypoint: str,
        job_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
        entrypoint_num_cpus: float = 0,
    ) -> str:
        job_id = job_id or f"rtpu-job-{uuid.uuid4().hex[:10]}"
        env = dict((runtime_env or {}).get("env_vars", {}))
        cwd = (runtime_env or {}).get("working_dir")
        supervisor_cls = ray_tpu.remote(num_cpus=entrypoint_num_cpus)(_JobSupervisor)
        supervisor = supervisor_cls.options(
            name=f"_rtpu_job_supervisor_{job_id}", namespace="job",
        ).remote(job_id, entrypoint, env, cwd)
        self._worker.gcs_call(
            "kv_put", _NS, job_id.encode(),
            json.dumps({
                "job_id": job_id, "status": JobStatus.PENDING,
                "entrypoint": entrypoint, "message": "", "updated_at": time.time(),
            }).encode(), True,
        )
        supervisor.run.remote()  # raylint: disable=RL501 (fire-and-forget; status lands in KV)
        return job_id

    def _info(self, job_id: str) -> Optional[dict]:
        raw = self._worker.gcs_call("kv_get", _NS, job_id.encode())
        return json.loads(raw) if raw else None

    def get_job_status(self, job_id: str) -> Optional[str]:
        info = self._info(job_id)
        return info["status"] if info else None

    def get_job_info(self, job_id: str) -> Optional[dict]:
        return self._info(job_id)

    def list_jobs(self) -> List[dict]:
        keys = self._worker.gcs_call("kv_keys", _NS, b"")
        out = []
        for key in keys:
            raw = self._worker.gcs_call("kv_get", _NS, key)
            if raw:
                out.append(json.loads(raw))
        return out

    def get_job_logs(self, job_id: str) -> str:
        try:
            supervisor = ray_tpu.get_actor(
                f"_rtpu_job_supervisor_{job_id}", namespace="job"
            )
            return ray_tpu.get(supervisor.logs.remote())
        except Exception:
            info = self._info(job_id)
            if info and info.get("log_path") and os.path.exists(info["log_path"]):
                with open(info["log_path"], errors="replace") as f:
                    return f.read()
            return ""

    def stop_job(self, job_id: str) -> bool:
        try:
            supervisor = ray_tpu.get_actor(
                f"_rtpu_job_supervisor_{job_id}", namespace="job"
            )
            return ray_tpu.get(supervisor.stop.remote())
        except Exception:
            return False

    def wait_until_status(self, job_id: str, statuses=JobStatus.TERMINAL,
                          timeout: float = 120) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in statuses:
                return status
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} not in {statuses} after {timeout}s")
