"""Decoder-only transformer (llama family), TPU-first.

The flagship model the framework trains and serves (reference trains torch models through
Ray Train and serves via vLLM; here the model is native: flax + Pallas flash attention +
logical-axis sharding). Every parameter is annotated with logical axis names which
parallel/mesh.py binds to the (dp, fsdp, tp, sp, pp, ep) hardware mesh — the same module
runs single-chip, FSDP, tensor-parallel, and sequence-parallel without code changes.

Architecture: RMSNorm, rotary embeddings, grouped-query attention, SwiGLU MLP, untied or
tied output head; bfloat16 activations with float32 RMSNorm accumulation (MXU-friendly).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention, reference_attention


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    mlp_dim: int = 1408
    max_seq: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    tie_embeddings: bool = False
    remat: bool = True
    # What the backward pass may keep from forward under remat:
    # "full"      recompute everything (lowest memory, ~20% slower/layer at 8B
    #             shape);
    # "attn"      save flash-attention outputs only;
    # "dots"      save every matmul output (XLA dots_saveable — fastest, but
    #             keeps the [S, mlp_dim] gate/up activations: ~330 MB/layer at
    #             the 8B shape, s2048);
    # "selective" save the attention-side tensors (post-rope q/k/v, attention
    #             out, o/down projections, pre-MLP norm) and RECOMPUTE the
    #             wide [S, mlp_dim] gate/up matmuls — ~100 MB/layer at the 8B
    #             shape: the memory/speed point that fits an fsdp=8 v5e pod.
    remat_policy: str = "full"
    scan_layers: bool = True
    fused_qkv: bool = False  # one projection matmul for q,k,v (and gate|up in the MLP);
    # measured slower than separate projections on v5e at gpt2 scale — off by default
    attention: str = "flash"  # flash | reference | ring | ulysses
    sp_axis: str = "sp"
    # MoE: >0 replaces the dense MLP with that many experts (expert-parallel over
    # the "ep" mesh axis; reference has no native EP — SURVEY.md §2.3).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coeff: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.hidden // self.n_heads

    def num_params(self) -> int:
        e = self.vocab_size * self.hidden
        attn = self.hidden * self.head_dim * (self.n_heads * 2 + self.n_kv_heads * 2)
        if self.moe_experts > 0:
            # router + per-expert in/out projections (2 matmuls each)
            mlp = self.hidden * self.moe_experts + (
                self.moe_experts * 2 * self.hidden * self.mlp_dim
            )
        else:
            mlp = 3 * self.hidden * self.mlp_dim
        norms = 2 * self.hidden
        per_layer = attn + mlp + norms
        head = 0 if self.tie_embeddings else e
        return e + self.n_layers * per_layer + self.hidden + head


# Named configs; parameter counts cited for parity with common baselines.
CONFIGS: dict[str, ModelConfig] = {
    "test-tiny": ModelConfig(
        vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=2, mlp_dim=128,
        max_seq=128, dtype=jnp.float32, remat=False, scan_layers=False,
        attention="reference",
    ),
    "gpt2-125m": ModelConfig(
        vocab_size=50257, hidden=768, n_layers=12, n_heads=12, n_kv_heads=12,
        mlp_dim=3072, max_seq=1024, tie_embeddings=True,
    ),
    "llama3-1b": ModelConfig(
        vocab_size=128256, hidden=2048, n_layers=16, n_heads=32, n_kv_heads=8,
        mlp_dim=8192, max_seq=8192, tie_embeddings=True,
    ),
    "llama3-8b": ModelConfig(
        vocab_size=128256, hidden=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        mlp_dim=14336, max_seq=8192,
    ),
}


def _rope_angles(positions: jax.Array, head_dim: int, theta: float):
    """cos/sin tables for rotary embedding: [B,S,half] f32 each.

    Computed ONCE per forward (Transformer.__call__) and broadcast through the
    layer scan — inside the scan the transcendentals re-ran every layer (XLA
    does not hoist loop-invariant code out of scans; ~4 ms/step measured at
    the bench shape)."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    if positions.ndim == 1:
        positions = positions[None, :]
    # Angle computation stays f32 (position * freq overflows bf16 precision
    # fast); the rotation itself runs in the activation dtype — the [B,S,H,D]
    # elementwise traffic is the cost, and bf16 halves it per layer.
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    return jnp.cos(angles), jnp.sin(angles)


def _rope_apply(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Apply the rotation. x: [B,S,H,D]; cos/sin: [B,S,D//2] f32."""
    cos = cos[:, :, None, :].astype(x.dtype)
    sin = sin[:, :, None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _rope_apply_bhsd(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Apply the rotation in the kernel-native layout. x: [B,H,S,D]."""
    cos = cos[:, None, :, :].astype(x.dtype)
    sin = sin[:, None, :, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding. x: [B, S, H, D]; positions: [B, S] or [S]."""
    cos, sin = _rope_angles(positions, x.shape[-1], theta)
    return _rope_apply(x, cos, sin)


class _HeadProj(nn.Module):
    """[B,S,E] -> [B,H,S,D] projection: the head/seq transpose folds into the
    matmul itself instead of materializing in HBM (the flash kernel consumes
    [B,H,S,D] natively). Param tree identical to the DenseGeneral it replaces
    (kernel [E,H,D] under the same name) — checkpoints are interchangeable."""

    heads: int
    head_dim: int
    dtype: Any
    param_dtype: Any
    axis_names: tuple

    @nn.compact
    def __call__(self, x):
        # DenseGeneral initializes multi-dim kernels on the FLATTENED 2-D
        # shape (fan-in = E) and reshapes; replicate exactly so this param is
        # bit-identical to the DenseGeneral it replaces under the same rng.
        def init(key, shape, dtype):
            flat = (shape[0], shape[1] * shape[2])
            return nn.initializers.lecun_normal()(key, flat, dtype).reshape(shape)

        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(init, self.axis_names),
            (x.shape[-1], self.heads, self.head_dim),
            self.param_dtype,
        )
        return jnp.einsum(
            "bse,ehd->bhsd", x.astype(self.dtype), kernel.astype(self.dtype)
        )


class _OutProjBhsd(nn.Module):
    """[B,H,S,D] -> [B,S,E]; kernel [H,D,E] matches DenseGeneral axis=(-2,-1)."""

    features: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):
        def init(key, shape, dtype):
            flat = (shape[0] * shape[1], shape[2])
            return nn.initializers.lecun_normal()(key, flat, dtype).reshape(shape)

        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(init, ("heads", "head_dim", "embed")),
            (x.shape[1], x.shape[-1], self.features),
            self.param_dtype,
        )
        return jnp.einsum(
            "bhsd,hde->bse", x.astype(self.dtype), kernel.astype(self.dtype)
        )


class RMSNorm(nn.Module):
    eps: float = 1e-5
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(), ("embed",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        # The mean-of-squares reduction runs in f32 (768 bf16 squares summed
        # in bf16 would lose ~2 decimal digits); the normalization multiply
        # runs in the activation dtype — for bf16 models that halves this
        # op's elementwise/HBM cost, and the values were about to be rounded
        # to bf16 anyway. f32 models are bit-identical to the f32-throughout
        # form.
        x32 = x.astype(jnp.float32)
        inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return x * (inv.astype(x.dtype) * scale.astype(x.dtype))


class Attention(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, positions, rope=None, kv_cache=None):
        cfg = self.cfg
        if cfg.attention == "flash" and kv_cache is None and not cfg.fused_qkv:
            return self._flash_bhsd(x, positions, rope), None
        dense = lambda features, names, name: nn.DenseGeneral(  # noqa: E731
            features,
            axis=-1,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), names
            ),
            name=name,
        )
        if cfg.fused_qkv:
            total = cfg.n_heads + 2 * cfg.n_kv_heads
            qkv = dense((total, cfg.head_dim), ("embed", "heads", "head_dim"), "qkv")(x)
            q = qkv[..., : cfg.n_heads, :]
            k = qkv[..., cfg.n_heads : cfg.n_heads + cfg.n_kv_heads, :]
            v = qkv[..., cfg.n_heads + cfg.n_kv_heads :, :]
        else:
            q = dense((cfg.n_heads, cfg.head_dim), ("embed", "heads", "head_dim"), "q")(x)
            k = dense((cfg.n_kv_heads, cfg.head_dim), ("embed", "kv_heads", "head_dim"), "k")(x)
            v = dense((cfg.n_kv_heads, cfg.head_dim), ("embed", "kv_heads", "head_dim"), "v")(x)
        if rope is None:
            rope = _rope_angles(positions, cfg.head_dim, cfg.rope_theta)
        q = _rope_apply(q, *rope)
        k = _rope_apply(k, *rope)
        if cfg.remat and cfg.remat_policy == "selective":
            from jax.ad_checkpoint import checkpoint_name

            # Saving post-rope q/k/v lets the flash backward kernel run
            # without recomputing projections+rope; k/v are small under GQA.
            q = checkpoint_name(q, "save")
            k = checkpoint_name(k, "save")
            v = checkpoint_name(v, "save")

        new_cache = None
        if kv_cache is not None:
            # Decode path: append to cache and attend over the full prefix.
            cache_k, cache_v, cache_len = kv_cache
            k = jax.lax.dynamic_update_slice(cache_k, k.astype(cache_k.dtype), (0, cache_len, 0, 0))
            v = jax.lax.dynamic_update_slice(cache_v, v.astype(cache_v.dtype), (0, cache_len, 0, 0))
            new_cache = (k, v, cache_len + x.shape[1])
            t = jnp.arange(k.shape[1])
            out = reference_attention(
                q, k, v, causal=True,
                positions_q=positions[0] if positions.ndim > 1 else positions,
                positions_kv=t,
            )
        elif cfg.attention == "reference":
            out = reference_attention(q, k, v, causal=True)
        elif cfg.attention == "ring":
            from ray_tpu.ops.ring_attention import ring_attention

            out = ring_attention(q, k, v, cfg.sp_axis, causal=True)
        elif cfg.attention == "ulysses":
            from ray_tpu.ops.ring_attention import ulysses_attention

            out = ulysses_attention(q, k, v, cfg.sp_axis, causal=True)
        else:
            out = flash_attention(q, k, v, True, None)
        if cfg.remat and cfg.remat_policy == "attn":
            from jax.ad_checkpoint import checkpoint_name

            out = checkpoint_name(out, "attn_out")
        elif cfg.remat and cfg.remat_policy == "selective":
            from jax.ad_checkpoint import checkpoint_name

            out = checkpoint_name(out, "save")

        proj = nn.DenseGeneral(
            cfg.hidden,
            axis=(-2, -1),
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("heads", "head_dim", "embed")
            ),
            name="o",
        )(out)
        return proj, new_cache

    def _flash_bhsd(self, x, positions, rope):
        """Transpose-free train path: projections emit [B,H,S,D] directly,
        the flash kernel runs in its native layout, and the output projection
        contracts straight back to [B,S,E] — the 11 per-layer HBM transposes
        of the [B,S,H,D] route never materialize. Same param tree."""
        from ray_tpu.ops.attention import flash_attention_bhsd

        cfg = self.cfg
        q = _HeadProj(cfg.n_heads, cfg.head_dim, cfg.dtype, cfg.param_dtype,
                      ("embed", "heads", "head_dim"), name="q")(x)
        k = _HeadProj(cfg.n_kv_heads, cfg.head_dim, cfg.dtype, cfg.param_dtype,
                      ("embed", "kv_heads", "head_dim"), name="k")(x)
        v = _HeadProj(cfg.n_kv_heads, cfg.head_dim, cfg.dtype, cfg.param_dtype,
                      ("embed", "kv_heads", "head_dim"), name="v")(x)
        if rope is None:
            rope = _rope_angles(positions, cfg.head_dim, cfg.rope_theta)
        q = _rope_apply_bhsd(q, *rope)
        k = _rope_apply_bhsd(k, *rope)
        if cfg.remat and cfg.remat_policy == "selective":
            from jax.ad_checkpoint import checkpoint_name

            q = checkpoint_name(q, "save")
            k = checkpoint_name(k, "save")
            v = checkpoint_name(v, "save")
        out = flash_attention_bhsd(q, k, v, True, None)
        if cfg.remat and cfg.remat_policy == "attn":
            from jax.ad_checkpoint import checkpoint_name

            out = checkpoint_name(out, "attn_out")
        elif cfg.remat and cfg.remat_policy == "selective":
            from jax.ad_checkpoint import checkpoint_name

            out = checkpoint_name(out, "save")
        return _OutProjBhsd(cfg.hidden, cfg.dtype, cfg.param_dtype,
                            name="o")(out)


class MLP(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda features, names, name: nn.DenseGeneral(  # noqa: E731
            features,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), names
            ),
            name=name,
        )
        if cfg.fused_qkv:
            gate_up = dense(2 * cfg.mlp_dim, ("embed", "mlp"), "gate_up")(x)
            gate, up = jnp.split(gate_up, 2, axis=-1)
        else:
            gate = dense(cfg.mlp_dim, ("embed", "mlp"), "gate")(x)
            up = dense(cfg.mlp_dim, ("embed", "mlp"), "up")(x)
        down = dense(cfg.hidden, ("mlp", "embed"), "down")(nn.silu(gate) * up)
        if cfg.remat and cfg.remat_policy == "selective":
            from jax.ad_checkpoint import checkpoint_name

            # Save the NARROW down-projection output; the wide [S, mlp_dim]
            # gate/up activations are recomputed in backward.
            down = checkpoint_name(down, "save")
        return down


class Block(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, positions, rope=None, kv_cache=None):
        cfg = self.cfg
        attn_out, new_cache = Attention(cfg, name="attn")(
            RMSNorm(cfg.norm_eps, name="attn_norm")(x), positions, rope,
            kv_cache
        )
        x = x + attn_out
        normed = RMSNorm(cfg.norm_eps, name="mlp_norm")(x)
        if cfg.remat and cfg.remat_policy == "selective":
            from jax.ad_checkpoint import checkpoint_name

            normed = checkpoint_name(normed, "save")
        if cfg.moe_experts > 0:
            from ray_tpu.ops.moe import MoEMLP

            mlp_out, aux = MoEMLP(
                d_model=cfg.hidden, d_ff=cfg.mlp_dim,
                num_experts=cfg.moe_experts, top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype, name="moe",
            )(normed)
        else:
            mlp_out = MLP(cfg, name="mlp")(normed)
            aux = jnp.zeros((), jnp.float32)
        x = x + mlp_out
        return x, (new_cache, aux)


class Transformer(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, tokens, positions=None, kv_caches=None, return_hidden=False):
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :].astype(jnp.int32)
        embed = self.param(
            "embedding",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            (cfg.vocab_size, cfg.hidden),
            cfg.param_dtype,
        )
        x = embed[tokens].astype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        # Rotary cos/sin computed once, broadcast into every layer (the scan
        # would otherwise recompute the transcendentals per layer).
        rope = _rope_angles(positions, cfg.head_dim, cfg.rope_theta)

        def remat_block():
            if cfg.remat_policy == "attn":
                policy = jax.checkpoint_policies.save_only_these_names(
                    "attn_out"
                )
            elif cfg.remat_policy == "selective":
                policy = jax.checkpoint_policies.save_only_these_names(
                    "save", "flash_residuals"
                )
            elif cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_saveable
            else:
                policy = None
            return nn.remat(Block, prevent_cse=False, policy=policy)

        new_caches = []
        if cfg.scan_layers and kv_caches is None:
            block = Block
            if cfg.remat:
                block = remat_block()
            ScannedBlocks = nn.scan(
                block,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
                in_axes=(nn.broadcast, nn.broadcast),
            )
            x, (_, aux_stack) = ScannedBlocks(cfg, name="layers")(
                x, positions, rope
            )
            moe_aux = jnp.sum(aux_stack)
        else:
            moe_aux = jnp.zeros((), jnp.float32)
            for i in range(cfg.n_layers):
                block_cls = Block
                if cfg.remat and kv_caches is None:
                    block_cls = remat_block()
                cache = kv_caches[i] if kv_caches is not None else None
                x, (new_cache, aux) = block_cls(cfg, name=f"layer_{i}")(
                    x, positions, rope, cache
                )
                new_caches.append(new_cache)
                moe_aux = moe_aux + aux
        if cfg.moe_experts > 0:
            # Reaches the training loss without changing the return signature:
            # apply(..., mutable=["losses"]) surfaces it; plain apply ignores it.
            self.sow("losses", "moe_aux", cfg.moe_aux_coeff * moe_aux)

        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        if return_hidden:
            # Training fast path: the caller computes a chunked fused
            # cross-entropy against the embedding table instead of
            # materializing [B,S,V] float32 logits (see fused_cross_entropy_loss).
            return x
        # Head matmul on the MXU bf16 path with f32 accumulation (an f32 matmul here
        # costs ~8x MXU throughput); loss math stays f32 downstream.
        if cfg.tie_embeddings:
            logits = jax.lax.dot_general(
                x.astype(cfg.dtype), embed.astype(cfg.dtype),
                (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            logits = nn.DenseGeneral(
                cfg.vocab_size,
                use_bias=False,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "vocab")
                ),
                name="lm_head",
            )(x).astype(jnp.float32)
        logits = nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))
        if kv_caches is not None:
            return logits, new_caches
        return logits


def cross_entropy_loss(logits, targets, mask=None):
    """Mean next-token loss. logits:[B,S,V] float32; targets:[B,S] int32."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def fused_cross_entropy_loss(hidden, table, targets, mask=None, *, chunk=256,
                             contract_dim=1, compute_dtype=jnp.bfloat16):
    """Chunked head-matmul + cross-entropy that never materializes full logits.

    HBM-bound at GPT-2 vocab sizes: [B,S,V] float32 logits are ~1.6 GB at
    B=8/S=1024/V=50257, written and re-read in forward and again as the softmax
    gradient in backward. Computing logits per sequence chunk under
    jax.checkpoint bounds live logits to [B,chunk,V] in both passes (backward
    recomputes each chunk's logits), trading a second head matmul for ~3 GB of
    HBM traffic per step — a net win on TPU where HBM bandwidth, not MXU FLOPs,
    limits this model size.

    hidden: [B,S,E] (pre-head, post-final-norm); table: the tied embedding
    [V,E] (contract_dim=1) or an untied lm_head kernel [E,V] (contract_dim=0);
    targets: [B,S] int32. Matches cross_entropy_loss numerically (same bf16
    matmul with f32 accumulation as the model head).
    """
    import math as _math

    B, S, E = hidden.shape
    c = _math.gcd(S, chunk)
    n = S // c
    hs = hidden.reshape(B, n, c, E).swapaxes(0, 1)  # [n,B,c,E]
    ts = targets.reshape(B, n, c).swapaxes(0, 1)  # [n,B,c]
    ms = None if mask is None else mask.reshape(B, n, c).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_sums(h, t, m):
        logits = jax.lax.dot_general(
            h.astype(compute_dtype), table.astype(compute_dtype),
            (((2,), (contract_dim,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [B,c,V]
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
        nll = logz - gold
        if m is not None:
            return jnp.sum(nll * m), jnp.sum(m)
        return jnp.sum(nll), jnp.asarray(nll.size, jnp.float32)

    def body(carry, xs):
        h, t, m = xs if ms is not None else (*xs, None)
        s, cnt = chunk_sums(h, t, m)
        return (carry[0] + s, carry[1] + cnt), None

    init = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
    xs = (hs, ts, ms) if ms is not None else (hs, ts)
    (total, count), _ = jax.lax.scan(body, init, xs)
    return total / jnp.maximum(count, 1.0)


def init_params(cfg: ModelConfig, rng=None, batch: int = 1, seq: int | None = None):
    model = Transformer(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    seq = seq or min(cfg.max_seq, 128)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    return model, model.init(rng, tokens)


def get_config(name: str, **overrides) -> ModelConfig:
    cfg = CONFIGS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg
