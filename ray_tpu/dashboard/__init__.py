"""Dashboard: HTTP observability over the cluster.

Design parity: reference `python/ray/dashboard/` (head.py + modules serving the
state/jobs/nodes APIs the React UI consumes). Rebuilt small: one async actor runs a
dependency-free HTTP server exposing the JSON API (`/api/...`) and a self-contained
HTML page that polls it — no build step, no JS dependencies. The heavy lifting is the
same state sources the `ray_tpu.util.state` API reads (GCS tables + task events).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

import ray_tpu

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.5rem; background: #fafafa; }
 h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.2rem; }
 table { border-collapse: collapse; width: 100%; background: #fff; }
 th, td { border: 1px solid #ddd; padding: 4px 8px; font-size: 0.85rem; text-align: left; }
 th { background: #f0f0f0; }
 .pill { padding: 1px 8px; border-radius: 10px; font-size: 0.8rem; }
 .ALIVE, .SUCCEEDED, .FINISHED { background: #d4efd4; }
 .DEAD, .FAILED { background: #f3d0d0; }
 .PENDING_CREATION, .RUNNING, .PENDING { background: #fdeec7; }
 .charts { display: flex; flex-wrap: wrap; gap: 1rem; }
 .chart { background: #fff; border: 1px solid #ddd; padding: 6px; }
 .chart .t { font-size: 0.8rem; color: #555; margin-bottom: 2px; }
 #logbox { background: #111; color: #d6d6d6; font: 0.78rem/1.3 monospace;
           padding: 8px; height: 220px; overflow-y: scroll; white-space: pre-wrap; }
 #timeline { background: #fff; border: 1px solid #ddd; }
 select { font-size: 0.85rem; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="summary"></div>
<h2>Metrics</h2>
<div class="charts">
  <div class="chart"><div class="t">CPU in use / total</div><svg id="c_cpu" width="320" height="90"></svg></div>
  <div class="chart"><div class="t">TPU in use / total</div><svg id="c_tpu" width="320" height="90"></svg></div>
  <div class="chart"><div class="t">Alive actors</div><svg id="c_actors" width="320" height="90"></svg></div>
  <div class="chart"><div class="t">Task events /s</div><svg id="c_tasks" width="320" height="90"></svg></div>
</div>
<h2>Task timeline <span style="font-weight:normal;font-size:0.8rem">(one lane per worker; green=done, red=failed, amber=running)</span></h2>
<canvas id="timeline" width="1000" height="160"></canvas>
<h2>Worker logs <select id="logsel"></select></h2>
<div id="logbox"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Serve</h2><table id="serve"></table>
<h2>Train runs</h2><table id="train"></table>
<h2>Data executions</h2><table id="data"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<script>
function esc(v) {
  return String(v).replace(/[&<>"']/g, c => (
    {"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));
}
function row(cells, tag) {
  return "<tr>" + cells.map(c => `<${tag||"td"}>${c}</${tag||"td"}>`).join("") + "</tr>";
}
function pill(s) { return `<span class="pill ${esc(s)}">${esc(s)}</span>`; }

// -- line charts over the server-side history ring ---------------------------
function drawChart(id, series, colors) {
  const svg = document.getElementById(id), W = 320, H = 90, P = 4;
  let max = 1;
  series.forEach(s => s.forEach(v => { if (v > max) max = v; }));
  const paths = series.map((s, i) => {
    if (!s.length) return "";
    const pts = s.map((v, j) => {
      const x = P + (W - 2 * P) * j / Math.max(1, s.length - 1);
      const y = H - P - (H - 2 * P) * v / max;
      return `${x.toFixed(1)},${y.toFixed(1)}`;
    });
    return `<polyline fill="none" stroke="${colors[i]}" stroke-width="1.5" points="${pts.join(" ")}"/>`;
  });
  svg.innerHTML = paths.join("") +
    `<text x="${W-P}" y="12" text-anchor="end" font-size="10" fill="#888">${max.toFixed(0)}</text>`;
}

// -- task timeline: lanes per worker, bars per task --------------------------
function drawTimeline(events) {
  const cv = document.getElementById("timeline"), ctx = cv.getContext("2d");
  ctx.clearRect(0, 0, cv.width, cv.height);
  const spans = {};  // task_id -> {start, end, state, worker}
  events.forEach(e => {
    const s = spans[e.task_id] = spans[e.task_id] ||
      {start: null, end: null, state: "RUNNING", worker: null, name: e.name};
    if (e.state === "RUNNING") {
      // lane = the EXECUTING worker (SUBMITTED events come from the driver)
      s.start = e.time; s.worker = e.worker_id || "?";
    } else if (e.state === "FINISHED" || e.state === "FAILED") {
      s.end = e.time; s.state = e.state;
    }
  });
  const list = Object.values(spans).filter(s => s.start);
  if (!list.length) return;
  const now = Date.now() / 1000;
  const t0 = Math.min(...list.map(s => s.start));
  const t1 = Math.max(now, ...list.map(s => s.end || now));
  const lanes = [...new Set(list.map(s => s.worker))].slice(0, 12);
  const laneH = Math.min(24, (cv.height - 14) / Math.max(1, lanes.length));
  const X = t => 60 + (cv.width - 70) * (t - t0) / Math.max(1e-9, t1 - t0);
  ctx.font = "9px monospace"; ctx.fillStyle = "#666";
  lanes.forEach((w, i) => ctx.fillText(w.slice(0, 8), 2, 12 + i * laneH + laneH / 2));
  list.forEach(s => {
    const lane = lanes.indexOf(s.worker);
    if (lane < 0) return;
    const xa = X(s.start), xb = X(s.end || now);
    ctx.fillStyle = s.state === "FINISHED" ? "#7cbf7c" : s.state === "FAILED" ? "#d98080" : "#e8c464";
    ctx.fillRect(xa, 6 + lane * laneH, Math.max(2, xb - xa), laneH - 4);
  });
  ctx.fillStyle = "#888";
  ctx.fillText(new Date(t0 * 1000).toLocaleTimeString(), 60, cv.height - 2);
  ctx.fillText(new Date(t1 * 1000).toLocaleTimeString(), cv.width - 70, cv.height - 2);
}

// -- log viewer --------------------------------------------------------------
let logWorker = "";
async function refreshLogs() {
  const sel = document.getElementById("logsel");
  const workers = await (await fetch("/api/log_workers")).json();
  const current = sel.value || logWorker;
  sel.innerHTML = workers.map(w =>
    `<option value="${esc(w.worker)}">${esc(w.kind)} pid=${esc(w.pid)} ${esc(w.worker.slice(0,10))} (${w.lines})</option>`
  ).join("");
  if (current) sel.value = current;
  logWorker = sel.value;
  if (!logWorker) return;
  const lines = await (await fetch(`/api/worker_log?worker=${logWorker}&limit=200`)).json();
  const box = document.getElementById("logbox");
  const pinned = box.scrollTop + box.clientHeight >= box.scrollHeight - 8;
  box.textContent = lines.join("\\n");
  if (pinned) box.scrollTop = box.scrollHeight;
}
document.getElementById("logsel").addEventListener("change", e => {
  logWorker = e.target.value; refreshLogs();
});

async function refresh() {
  const s = await (await fetch("/api/cluster")).json();
  document.getElementById("summary").innerHTML =
    `<b>${s.alive_nodes}</b> nodes · CPU ${JSON.stringify(s.resources_available.CPU||0)}` +
    ` / ${JSON.stringify(s.resources_total.CPU||0)} available` +
    ` · actors ${JSON.stringify(s.actors)} · tasks ${JSON.stringify(s.tasks)}`;
  const hist = await (await fetch("/api/metrics_history")).json();
  drawChart("c_cpu", [hist.map(h => h.cpu_used), hist.map(h => h.cpu_total)], ["#4a7dbd", "#bbb"]);
  drawChart("c_tpu", [hist.map(h => h.tpu_used), hist.map(h => h.tpu_total)], ["#9a5fb5", "#bbb"]);
  drawChart("c_actors", [hist.map(h => h.actors_alive)], ["#3e9e5f"]);
  drawChart("c_tasks", [hist.map(h => h.task_events_rate)], ["#cf8a3b"]);
  const nodes = await (await fetch("/api/nodes")).json();
  document.getElementById("nodes").innerHTML = row(["node", "address", "total", "available", "state"], "th") +
    nodes.map(n => row([esc(n.node_id), esc(n.address), esc(JSON.stringify(n.resources_total)),
                        esc(JSON.stringify(n.resources_available)),
                        pill(n.alive ? "ALIVE" : "DEAD")])).join("");
  const actors = await (await fetch("/api/actors")).json();
  document.getElementById("actors").innerHTML = row(["actor", "class", "name", "state", "restarts"], "th") +
    actors.map(a => row([esc(a.actor_id), esc(a.class_name), esc(a.name || ""),
                         pill(a.state), esc(a.num_restarts)])).join("");
  // Library views are independent: one failing fetch must not freeze the
  // core tables below it.
  try {
    const sv = await (await fetch("/api/serve")).json();
    const svRows = [];
    for (const [app, info] of Object.entries(sv.apps || {})) {
      for (const [dep, d] of Object.entries(info.deployments || {})) {
        svRows.push(row([esc(app), esc(info.route_prefix || ""), esc(dep),
                         `${esc(d.num_replicas)}/${esc(d.target)}`]));
      }
    }
    document.getElementById("serve").innerHTML =
      row(["app", "route", "deployment", "replicas/target"], "th") + svRows.join("");
    const tr = await (await fetch("/api/train")).json();
    document.getElementById("train").innerHTML =
      row(["run", "state", "workers", "done", "latest metrics"], "th") +
      tr.map(t => row([esc(t.run_name), pill(t.state || "?"), esc(t.num_workers ?? ""),
                       esc(t.done ?? ""), esc(JSON.stringify(t.latest_metrics || {}))])).join("");
    const dt = await (await fetch("/api/data")).json();
    document.getElementById("data").innerHTML =
      row(["finished", "duration s", "pipeline", "rows out", "error"], "th") +
      dt.slice(-12).reverse().map(d => {
        const last = d.ops[d.ops.length - 1] || {};
        return row([esc(new Date(d.finished_at * 1000).toLocaleTimeString()),
                    esc(d.duration_s),
                    esc(d.ops.map(o => o.name).join(" → ")),
                    esc(last.out_rows ?? ""), esc(d.error || "")]);
      }).join("");
  } catch (e) { /* library views are best-effort */ }
  const jobs = await (await fetch("/api/jobs")).json();
  document.getElementById("jobs").innerHTML = row(["job", "status", "entrypoint"], "th") +
    jobs.map(j => row([esc(j.job_id), pill(j.status), esc(j.entrypoint)])).join("");
  const tasks = await (await fetch("/api/tasks?limit=400")).json();
  drawTimeline(tasks);
  document.getElementById("tasks").innerHTML = row(["task", "name", "state"], "th") +
    tasks.slice(-50).reverse().map(t => row([esc(t.task_id), esc(t.name), pill(t.state)])).join("");
  await refreshLogs();
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class DashboardActor:
    """Async actor serving the dashboard HTTP endpoint."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._host = host
        self._port = port
        self._server = None
        # Server-side metrics history ring: ~12 min at 3s resolution, sampled
        # from the same GCS state the JSON API reads (reference:
        # dashboard/modules/metrics serves Grafana panels; here the chart data
        # lives in-process and the page renders SVG).
        from collections import deque

        self._history = deque(maxlen=240)
        self._last_events_total = None

    async def start(self) -> int:
        if self._server is not None:
            return self._port
        self._server = await asyncio.start_server(self._handle, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        # Hold the task reference: loops keep only weak refs, and a GC'd
        # sampler silently freezes every chart.
        self._sampler = asyncio.get_running_loop().create_task(self._sample_loop())
        return self._port

    async def _sample_loop(self, interval_s: float = 3.0):
        """Cheap per-tick sampling: counters and resource maps only — never the
        event payloads (a busy cluster retains up to 100k of them)."""
        import time as _time

        loop = asyncio.get_running_loop()

        def sample():
            import ray_tpu
            from ray_tpu.util import state as state_mod

            nodes = state_mod.list_nodes()
            actors = state_mod.list_actors()
            return {
                "total": ray_tpu.cluster_resources(),
                "avail": ray_tpu.available_resources(),
                "alive_nodes": sum(1 for n in nodes if n.get("alive", True)),
                "actors_alive": sum(1 for a in actors if a.get("state") == "ALIVE"),
                "events_total": _gcs_call("task_event_stats")["total"],
            }

        while True:
            try:
                s = await loop.run_in_executor(None, sample)
                total, avail = s["total"], s["avail"]
                events = s["events_total"]
                if self._last_events_total is None:
                    rate = 0.0
                else:
                    rate = max(0.0, (events - self._last_events_total) / interval_s)
                self._last_events_total = events
                self._history.append({
                    "ts": _time.time(),
                    "cpu_total": float(total.get("CPU", 0) or 0),
                    "cpu_used": float((total.get("CPU", 0) or 0) - (avail.get("CPU", 0) or 0)),
                    "tpu_total": float(total.get("TPU", 0) or 0),
                    "tpu_used": float((total.get("TPU", 0) or 0) - (avail.get("TPU", 0) or 0)),
                    "actors_alive": s["actors_alive"],
                    "alive_nodes": s["alive_nodes"],
                    "task_events_rate": rate,
                })
            except Exception:
                pass  # sampling must never kill the server
            await asyncio.sleep(interval_s)

    async def _state(self, path: str, query: dict):
        from ray_tpu.util import state

        loop = asyncio.get_running_loop()
        if path == "/api/cluster":
            return await loop.run_in_executor(None, state.cluster_summary)
        if path == "/api/nodes":
            return await loop.run_in_executor(None, state.list_nodes)
        if path == "/api/actors":
            return await loop.run_in_executor(None, state.list_actors)
        if path == "/api/tasks":
            limit = int(query.get("limit", "200"))
            return await loop.run_in_executor(None, lambda: state.list_tasks(limit=limit))
        if path == "/api/objects":
            return await loop.run_in_executor(None, state.list_objects)
        if path == "/api/jobs":
            return await loop.run_in_executor(None, state.list_jobs)
        if path == "/api/metrics_history":
            return list(self._history)
        if path == "/api/serve":
            return await loop.run_in_executor(None, _serve_view)
        if path == "/api/train":
            return await loop.run_in_executor(None, _train_view)
        if path == "/api/data":
            return await loop.run_in_executor(None, _data_view)
        if path == "/api/log_workers":
            return await loop.run_in_executor(
                None, lambda: _gcs_call("list_log_workers")
            )
        if path == "/api/worker_log":
            worker = query.get("worker", "")
            limit = int(query.get("limit", "200"))
            return await loop.run_in_executor(
                None, lambda: _gcs_call("get_worker_log", worker, limit)
            )
        return None

    async def _handle(self, reader, writer):
        from ray_tpu._private.http import read_http_request, write_http_response

        try:
            request = await read_http_request(reader)
            if request is None:
                writer.close()
                return
            if request.path in ("/", "/index.html"):
                body, ctype, status = _PAGE.encode(), "text/html", 200
            elif request.path == "/metrics":
                # Prometheus exposition of every flushed cluster metric.
                from ray_tpu.util import metrics as metrics_mod

                loop = asyncio.get_running_loop()
                text = await loop.run_in_executor(None, metrics_mod.prometheus_text)
                body, ctype, status = text.encode(), "text/plain; version=0.0.4", 200
            else:
                data = await self._state(request.path, request.query)
                if data is None:
                    body, ctype, status = b"not found", "text/plain", 404
                else:
                    body = json.dumps(data, default=str).encode()
                    ctype, status = "application/json", 200
        except Exception as e:
            body, ctype, status = str(e).encode(), "text/plain", 500
        try:
            await write_http_response(writer, status, body, ctype)
        finally:
            writer.close()

    async def get_port(self) -> int:
        return self._port


def _gcs_call(method: str, *args):
    from ray_tpu.util.state import _gcs

    return _gcs(method, *args)


# -- per-library views (reference: dashboard modules for serve/train/data) --


def _serve_view() -> dict:
    """Apps -> deployments -> replica counts + the bound proxy ports."""
    try:
        from ray_tpu import serve

        apps = serve.status()
        return {"apps": apps, "proxy_ports": serve.proxy_ports()}
    except Exception:
        return {"apps": {}, "proxy_ports": {}}


def _train_view() -> list:
    """Live/finished train runs from the detached controllers' status()."""
    out = []
    try:
        for a in _gcs_call("list_actors"):
            name = a.get("name") or ""
            if a.get("namespace") != "_train" or not name.startswith(
                "TRAIN_CONTROLLER:"
            ):
                continue
            entry = {"run_name": name.split(":", 1)[1], "state": a.get("state")}
            if a.get("state") == "ALIVE":
                try:
                    handle = ray_tpu.get_actor(name, namespace="_train")
                    # Short timeout: one wedged controller must not freeze
                    # every dashboard refresh for the full actor-call window.
                    entry.update(ray_tpu.get(handle.status.remote(), timeout=2))
                except Exception:
                    pass
            out.append(entry)
    except Exception:
        pass
    return out


def _data_view() -> list:
    """Recent dataset executions published by the streaming executor."""
    import json as _json

    out = []
    try:
        for key in sorted(_gcs_call("kv_keys", "data_stats"))[-20:]:
            raw = _gcs_call("kv_get", "data_stats", key)
            if raw:
                out.append(_json.loads(raw))
    except Exception:
        pass
    return out


_state: dict = {}
# Two threads racing start_dashboard would both miss the cache and one
# would overwrite the other's {actor, port} (get_if_exists dedups the actor,
# but the loser's port write could land after a concurrent stop_dashboard).
_state_lock = threading.Lock()


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> int:
    """Start (or return) the cluster dashboard; returns the bound port."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    # The cache is per cluster SESSION: after shutdown()+init() the old actor is
    # gone and a cached port would point at nothing.
    session = ray_tpu.global_worker().session_token
    with _state_lock:
        if _state.get("session") != session:
            _state.clear()
            _state["session"] = session
        if _state.get("actor") is None:
            from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

            cls = ray_tpu.remote(num_cpus=0)(DashboardActor)
            actor = cls.options(
                name="RTPU_DASHBOARD", namespace="dashboard", get_if_exists=True,
                max_concurrency=100,
                # Pin to the CALLER's node: the server binds loopback, so the returned
                # port must be reachable from where start_dashboard was invoked.
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=ray_tpu.global_worker().node_id, soft=False
                ),
            ).remote(host, port)
            _state["actor"] = actor
            _state["port"] = ray_tpu.get(actor.start.remote())
        return _state["port"]


def stop_dashboard():
    with _state_lock:
        actor = _state.pop("actor", None)
        _state.pop("port", None)
    if actor is not None:
        try:
            ray_tpu.kill(actor)
        except Exception:
            pass


__all__ = ["DashboardActor", "start_dashboard", "stop_dashboard"]
