"""Dashboard: HTTP observability over the cluster.

Design parity: reference `python/ray/dashboard/` (head.py + modules serving the
state/jobs/nodes APIs the React UI consumes). Rebuilt small: one async actor runs a
dependency-free HTTP server exposing the JSON API (`/api/...`) and a self-contained
HTML page that polls it — no build step, no JS dependencies. The heavy lifting is the
same state sources the `ray_tpu.util.state` API reads (GCS tables + task events).
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

import ray_tpu

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.5rem; background: #fafafa; }
 h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.2rem; }
 table { border-collapse: collapse; width: 100%; background: #fff; }
 th, td { border: 1px solid #ddd; padding: 4px 8px; font-size: 0.85rem; text-align: left; }
 th { background: #f0f0f0; }
 .pill { padding: 1px 8px; border-radius: 10px; font-size: 0.8rem; }
 .ALIVE, .SUCCEEDED, .FINISHED { background: #d4efd4; }
 .DEAD, .FAILED { background: #f3d0d0; }
 .PENDING_CREATION, .RUNNING, .PENDING { background: #fdeec7; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="summary"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<script>
function esc(v) {
  return String(v).replace(/[&<>"']/g, c => (
    {"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));
}
function row(cells, tag) {
  return "<tr>" + cells.map(c => `<${tag||"td"}>${c}</${tag||"td"}>`).join("") + "</tr>";
}
function pill(s) { return `<span class="pill ${esc(s)}">${esc(s)}</span>`; }
async function refresh() {
  const s = await (await fetch("/api/cluster")).json();
  document.getElementById("summary").innerHTML =
    `<b>${s.alive_nodes}</b> nodes · CPU ${JSON.stringify(s.resources_available.CPU||0)}` +
    ` / ${JSON.stringify(s.resources_total.CPU||0)} available` +
    ` · actors ${JSON.stringify(s.actors)} · tasks ${JSON.stringify(s.tasks)}`;
  const nodes = await (await fetch("/api/nodes")).json();
  document.getElementById("nodes").innerHTML = row(["node", "address", "total", "available", "state"], "th") +
    nodes.map(n => row([esc(n.node_id), esc(n.address), esc(JSON.stringify(n.resources_total)),
                        esc(JSON.stringify(n.resources_available)),
                        pill(n.alive ? "ALIVE" : "DEAD")])).join("");
  const actors = await (await fetch("/api/actors")).json();
  document.getElementById("actors").innerHTML = row(["actor", "class", "name", "state", "restarts"], "th") +
    actors.map(a => row([esc(a.actor_id), esc(a.class_name), esc(a.name || ""),
                         pill(a.state), esc(a.num_restarts)])).join("");
  const jobs = await (await fetch("/api/jobs")).json();
  document.getElementById("jobs").innerHTML = row(["job", "status", "entrypoint"], "th") +
    jobs.map(j => row([esc(j.job_id), pill(j.status), esc(j.entrypoint)])).join("");
  const tasks = await (await fetch("/api/tasks?limit=50")).json();
  document.getElementById("tasks").innerHTML = row(["task", "name", "state"], "th") +
    tasks.slice(-50).reverse().map(t => row([esc(t.task_id), esc(t.name), pill(t.state)])).join("");
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class DashboardActor:
    """Async actor serving the dashboard HTTP endpoint."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._host = host
        self._port = port
        self._server = None

    async def start(self) -> int:
        if self._server is not None:
            return self._port
        self._server = await asyncio.start_server(self._handle, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self._port

    async def _state(self, path: str, query: dict):
        from ray_tpu.util import state

        loop = asyncio.get_running_loop()
        if path == "/api/cluster":
            return await loop.run_in_executor(None, state.cluster_summary)
        if path == "/api/nodes":
            return await loop.run_in_executor(None, state.list_nodes)
        if path == "/api/actors":
            return await loop.run_in_executor(None, state.list_actors)
        if path == "/api/tasks":
            limit = int(query.get("limit", "200"))
            return await loop.run_in_executor(None, lambda: state.list_tasks(limit=limit))
        if path == "/api/objects":
            return await loop.run_in_executor(None, state.list_objects)
        if path == "/api/jobs":
            return await loop.run_in_executor(None, state.list_jobs)
        return None

    async def _handle(self, reader, writer):
        from ray_tpu._private.http import read_http_request, write_http_response

        try:
            request = await read_http_request(reader)
            if request is None:
                writer.close()
                return
            if request.path in ("/", "/index.html"):
                body, ctype, status = _PAGE.encode(), "text/html", 200
            else:
                data = await self._state(request.path, request.query)
                if data is None:
                    body, ctype, status = b"not found", "text/plain", 404
                else:
                    body = json.dumps(data, default=str).encode()
                    ctype, status = "application/json", 200
        except Exception as e:
            body, ctype, status = str(e).encode(), "text/plain", 500
        try:
            await write_http_response(writer, status, body, ctype)
        finally:
            writer.close()

    async def get_port(self) -> int:
        return self._port


_state: dict = {}


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> int:
    """Start (or return) the cluster dashboard; returns the bound port."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    # The cache is per cluster SESSION: after shutdown()+init() the old actor is
    # gone and a cached port would point at nothing.
    session = ray_tpu.global_worker().session_token
    if _state.get("session") != session:
        _state.clear()
        _state["session"] = session
    if _state.get("actor") is None:
        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

        cls = ray_tpu.remote(num_cpus=0)(DashboardActor)
        actor = cls.options(
            name="RTPU_DASHBOARD", namespace="dashboard", get_if_exists=True,
            max_concurrency=100,
            # Pin to the CALLER's node: the server binds loopback, so the returned
            # port must be reachable from where start_dashboard was invoked.
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=ray_tpu.global_worker().node_id, soft=False
            ),
        ).remote(host, port)
        _state["actor"] = actor
        _state["port"] = ray_tpu.get(actor.start.remote())
    return _state["port"]


def stop_dashboard():
    actor = _state.pop("actor", None)
    _state.pop("port", None)
    if actor is not None:
        try:
            ray_tpu.kill(actor)
        except Exception:
            pass


__all__ = ["DashboardActor", "start_dashboard", "stop_dashboard"]
