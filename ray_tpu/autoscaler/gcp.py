"""GCE TPU-VM node provider: provision whole TPU slices as cluster nodes.

Design parity: reference `python/ray/autoscaler/_private/gcp/node_provider.py`
(+ tpu.py accelerator discovery) — nodes are TPU VM slices created through the
Cloud TPU REST API (tpu.googleapis.com v2); each slice boots a startup script
that joins the cluster (`ray_tpu start --address=<head>`), advertising its
chips and slice-head resource so gang scheduling works the moment it registers.

The HTTP transport is injectable: production uses urllib against
tpu.googleapis.com with a metadata-server access token; tests drive the
provider against recorded responses (this environment has zero egress, the
same way the reference's provider unit tests mock the discovery client).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler import NodeProvider

_TPU_API = "https://tpu.googleapis.com/v2"


def _metadata_token() -> str:
    """Access token from the GCE metadata server (TPU VMs and GCE heads)."""
    import urllib.request

    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())["access_token"]


def _default_transport(method: str, url: str, body: Optional[dict]) -> dict:
    import urllib.request

    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={
            "Authorization": f"Bearer {_metadata_token()}",
            "Content-Type": "application/json",
        },
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read() or b"{}")


class GCETPUNodeProvider(NodeProvider):
    """Each provider node is one whole TPU slice (possibly multi-host).

    Config:
        project, zone: GCE placement.
        accelerator_type: e.g. "v5litepod-16" — every created node is one slice
            of this topology.
        runtime_version: TPU VM image, e.g. "tpu-ubuntu2204-base".
        head_address: "host:port" the slice's hosts join on boot.
        cluster_name: label + name prefix for created slices.
        transport: fn(method, url, body) -> dict, injectable for tests.
    """

    def __init__(self, project: str, zone: str, accelerator_type: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 head_address: str = "", cluster_name: str = "ray-tpu",
                 transport: Optional[Callable] = None):
        self._project = project
        self._zone = zone
        self._accel = accelerator_type
        self._runtime = runtime_version
        self._head = head_address
        self._cluster = cluster_name
        self._transport = transport or _default_transport
        self._parent = f"projects/{project}/locations/{zone}"

    # -- SPI ----------------------------------------------------------------
    def create_node(self, resources: Dict[str, float]) -> str:
        node_id = f"{self._cluster}-{uuid.uuid4().hex[:8]}"
        # TPU/pod/head resources are derived per host by accelerator discovery
        # (accelerators/tpu.py: chips from the local topology, the slice-head
        # resource only on TPU_WORKER_ID==0). The startup script runs on EVERY
        # host of a multi-host slice, so baking them into --resources would make
        # all N hosts advertise the gang-scheduling head resource — one slice
        # would present N heads, breaking slice-atomic placement.
        custom = {
            k: v for k, v in resources.items()
            # Discovery outputs are exactly "TPU" (chip count) and "TPU-*"
            # (pod type, "-head", slice name — accelerators/tpu.py
            # node_resources); every other name is a user-defined custom
            # resource and passes through.
            if k not in ("CPU", "TPU") and not k.startswith("TPU-")
        }
        startup = (
            "#! /bin/bash\n"
            f"ray_tpu start --address={self._head} "
            f"--resources='{json.dumps(custom)}'\n"
        )
        body = {
            "acceleratorType": self._accel,
            "runtimeVersion": self._runtime,
            "labels": {"ray-tpu-cluster": self._cluster},
            "metadata": {"startup-script": startup},
        }
        self._transport(
            "POST", f"{_TPU_API}/{self._parent}/nodes?nodeId={node_id}", body
        )
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._transport(
            "DELETE", f"{_TPU_API}/{self._parent}/nodes/{node_id}", None
        )

    def non_terminated_nodes(self) -> List[str]:
        resp = self._transport("GET", f"{_TPU_API}/{self._parent}/nodes", None)
        out = []
        for node in resp.get("nodes", []):
            labels = node.get("labels") or {}
            if labels.get("ray-tpu-cluster") != self._cluster:
                continue
            if node.get("state") in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            out.append(node["name"].rsplit("/", 1)[-1])
        return out

    def cluster_address(self, node_id: str) -> Optional[tuple]:
        """First worker's internal IP: the raylet of slice host 0. The raylet
        port is unknown to the provider — (ip, None) tells the reconciler to
        match cluster nodes by IP alone."""
        try:
            node = self._transport(
                "GET", f"{_TPU_API}/{self._parent}/nodes/{node_id}", None
            )
        except Exception:
            return None
        endpoints = node.get("networkEndpoints") or []
        if not endpoints:
            return None
        return (endpoints[0].get("ipAddress"), None)


class RecordedTransport:
    """Test double: replays canned responses and records every request —
    the 'dryrun against recorded GCE responses' harness."""

    def __init__(self, responses: Optional[Dict[str, Any]] = None):
        self.requests: List[tuple] = []
        self._responses = responses or {}
        self._nodes: Dict[str, dict] = {}  # emulated live state

    def __call__(self, method: str, url: str, body: Optional[dict]) -> dict:
        self.requests.append((method, url, body))
        key = f"{method} {url}"
        if key in self._responses:
            return self._responses[key]
        # Default emulation: stateful create/list/get/delete.
        if method == "POST" and "nodes?nodeId=" in url:
            node_id = url.rsplit("nodeId=", 1)[-1]
            self._nodes[node_id] = {
                "name": f"nodes/{node_id}",
                "state": "READY",
                "labels": (body or {}).get("labels", {}),
                "acceleratorType": (body or {}).get("acceleratorType"),
                "networkEndpoints": [{"ipAddress": f"10.0.0.{len(self._nodes) + 2}"}],
                "metadata": (body or {}).get("metadata", {}),
            }
            return {"name": f"operations/create-{node_id}", "done": True}
        if method == "GET" and url.endswith("/nodes"):
            return {"nodes": list(self._nodes.values())}
        if method == "GET":
            node_id = url.rsplit("/", 1)[-1]
            if node_id in self._nodes:
                return self._nodes[node_id]
            raise KeyError(f"no such node {node_id}")
        if method == "DELETE":
            node_id = url.rsplit("/", 1)[-1]
            self._nodes.pop(node_id, None)
            return {"name": f"operations/delete-{node_id}", "done": True}
        raise ValueError(f"unhandled request {method} {url}")
