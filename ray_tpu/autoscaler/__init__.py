"""Autoscaler: declarative reconciliation of cluster size to resource demand.

Design parity: reference autoscaler v2 (`python/ray/autoscaler/v2/` — a reconciler
over an InstanceManager driven by the GCS autoscaler state, `autoscaler.py:47`
`update_autoscaling_state`) with the NodeProvider SPI of v1
(`python/ray/autoscaler/_private/node_provider.py`). The GCS exports unplaceable
demand (queued task resources + PENDING actors, `rpc_cluster_demand`); the
reconciler adds nodes until demand fits and removes nodes idle past a timeout.
`LocalNodeProvider` launches worker nodes as local processes — the
FakeMultiNodeProvider testing pattern (SURVEY.md §4.3) — while cloud providers
implement the same three methods against their APIs.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu

_REQUEST_KEY = b"autoscaler_resource_request"
_NS = "autoscaler"


# -- provider SPI ----------------------------------------------------------


class NodeProvider:
    """Provider SPI. create/terminate/list drive scaling; cluster_address maps a
    provider node to its raylet (host, port) so the reconciler can tell which
    CLUSTER node a provider node is — providers that return None opt out of
    downscale (nodes are only ever added)."""

    def create_node(self, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def cluster_address(self, node_id: str) -> Optional[tuple]:
        return None


class LocalNodeProvider(NodeProvider):
    """Worker nodes as local raylet processes on this machine (test/laptop cloud)."""

    def __init__(self, cluster):
        self._cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self._nodes: Dict[str, Any] = {}
        self._counter = 0

    def create_node(self, resources: Dict[str, float]) -> str:
        handle = self._cluster.add_node(
            num_cpus=int(resources.get("CPU", 1)),
            resources={k: v for k, v in resources.items() if k != "CPU"},
        )
        self._counter += 1
        name = f"local-{self._counter}"
        self._nodes[name] = handle
        return name

    def terminate_node(self, node_id: str) -> None:
        handle = self._nodes.pop(node_id, None)
        if handle is not None:
            self._cluster.remove_node(handle)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def cluster_address(self, node_id: str) -> Optional[tuple]:
        handle = self._nodes.get(node_id)
        if handle is None:
            return None
        return ("127.0.0.1", handle.raylet_port)


# -- config + sdk ----------------------------------------------------------


@dataclass
class AutoscalingConfig:
    min_workers: int = 0
    max_workers: int = 4
    worker_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1})
    idle_timeout_s: float = 30.0
    boot_grace_s: float = 300.0  # address-less remote nodes count as in-flight this long
    poll_interval_s: float = 1.0
    upscaling_speed: int = 2  # max nodes added per reconcile round


def request_resources(*, num_cpus: Optional[float] = None,
                      bundles: Optional[List[Dict[str, float]]] = None):
    """Explicit demand hint (parity: ray.autoscaler.sdk.request_resources)."""
    import json

    demand: Dict[str, float] = {}
    if num_cpus:
        demand["CPU"] = float(num_cpus)
    for b in bundles or []:
        for r, amt in b.items():
            demand[r] = demand.get(r, 0.0) + float(amt)
    ray_tpu.global_worker().gcs_call(
        "kv_put", _NS, _REQUEST_KEY, json.dumps(demand).encode(), True
    )


# -- reconciler ------------------------------------------------------------


class Autoscaler:
    def __init__(self, provider: NodeProvider, config: Optional[AutoscalingConfig] = None):
        self._provider = provider
        self._config = config or AutoscalingConfig()
        self._idle_since: Dict[str, float] = {}  # provider node id -> first idle t
        self._created_at: Dict[str, float] = {}  # provider node id -> launch t
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_scale_ups = 0
        self.num_scale_downs = 0

    # -- demand/state reads ------------------------------------------------
    def _demand(self, demand_info: Optional[dict] = None) -> Dict[str, float]:
        import json

        worker = ray_tpu.global_worker()
        if demand_info is None:
            demand_info = worker.gcs_call("cluster_demand")
        out = dict(demand_info["pending"])
        raw = worker.gcs_call("kv_get", _NS, _REQUEST_KEY)
        if raw:
            requested = json.loads(raw)
            avail = worker.gcs_call("cluster_resources")["total"]
            # request_resources is a floor on TOTAL cluster resources
            for r, amt in requested.items():
                shortfall = amt - avail.get(r, 0.0)
                if shortfall > 0:
                    out[r] = out.get(r, 0.0) + shortfall
        return out

    def reconcile_once(self) -> Dict[str, int]:
        cfg = self._config
        worker = ray_tpu.global_worker()
        demand_info = worker.gcs_call("cluster_demand")
        demand = self._demand(demand_info)
        gcs_nodes = worker.gcs_call("get_nodes")
        provider_nodes = self._provider.non_terminated_nodes()
        actions = {"added": 0, "removed": 0}
        # Floor: min_workers are provisioned up front, demand or not
        # (reference: `ray up` brings min_workers online at launch).
        short = cfg.min_workers - len(provider_nodes)
        if short > 0:
            for _ in range(min(short, cfg.upscaling_speed)):
                try:
                    pid = self._provider.create_node(dict(cfg.worker_resources))
                except Exception:
                    # Pool exhausted / transient provisioning failure: the
                    # floor must not abort the rest of this tick (demand
                    # upscale + idle downscale still need to run).
                    break
                self._created_at[pid] = time.monotonic()
                self.num_scale_ups += 1
                actions["added"] += 1
            provider_nodes = self._provider.non_terminated_nodes()
        # Upscale: enough worker nodes to absorb the unplaceable demand — minus
        # nodes already LAUNCHED but not yet registered with the GCS (counting
        # them again would over-provision to max_workers while they boot).
        if demand:
            per_node = cfg.worker_resources
            need = 0
            for r, amt in demand.items():
                cap = per_node.get(r, 0.0)
                if cap > 0:
                    need = max(need, math.ceil(amt / cap))
            registered = {
                tuple(n["address"]) for n in gcs_nodes if n["alive"] and not n["is_head"]
            }
            registered_ips = {a[0] for a in registered}
            now_mono = time.monotonic()
            in_flight = 0
            for pid in provider_nodes:
                addr = self._provider.cluster_address(pid)
                if addr is None:
                    # Address unknown (remote slice still booting): count it as
                    # in-flight only within the boot grace — a node that never
                    # registers must not suppress upscaling forever.
                    created = self._created_at.get(pid)
                    if created is not None and now_mono - created < cfg.boot_grace_s:
                        in_flight += 1
                elif addr[1] in (None, 0):
                    if addr[0] not in registered_ips:
                        in_flight += 1
                elif tuple(addr) not in registered:
                    in_flight += 1
            need = max(0, need - in_flight)
            room = cfg.max_workers - len(provider_nodes)
            to_add = max(0, min(need, room, cfg.upscaling_speed))
            for _ in range(to_add):
                pid = self._provider.create_node(dict(per_node))
                self._created_at[pid] = time.monotonic()
                self.num_scale_ups += 1
                actions["added"] += 1
        # Downscale: provider nodes idle past the timeout. Idle = no running work
        # (available == total), nothing queued, AND not occupied by live actors or
        # resident objects (zero-resource actors reserve nothing; a node holding
        # the only copy of an object must survive until it's fetched/freed).
        occupied = set(demand_info.get("occupied_nodes", []))
        idle_cluster_nodes = {
            tuple(n["address"]) for n in gcs_nodes
            if n["alive"] and not n["is_head"]
            and n["resources_available"] == n["resources_total"]
            and not any(n.get("pending_demand", {}).values())
            and n["node_id"].hex() not in occupied
        }
        idle_ips = {a[0] for a in idle_cluster_nodes}
        now = time.monotonic()
        provider_nodes = self._provider.non_terminated_nodes()
        removable = len(provider_nodes) - max(cfg.min_workers, 0)
        for node_id in provider_nodes:
            if removable <= 0:
                break
            addr = self._provider.cluster_address(node_id)
            idle = addr is not None and (
                addr[0] in idle_ips if addr[1] in (None, 0)
                else tuple(addr) in idle_cluster_nodes
            )
            if idle:
                first = self._idle_since.setdefault(node_id, now)
                if now - first >= cfg.idle_timeout_s:
                    self._provider.terminate_node(node_id)
                    self._idle_since.pop(node_id, None)
                    self.num_scale_downs += 1
                    actions["removed"] += 1
                    removable -= 1
            else:
                self._idle_since.pop(node_id, None)
        return actions

    # -- loop --------------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    def _run(self):
        import traceback

        consecutive_failures = 0
        while not self._stop.is_set():
            try:
                self.reconcile_once()
                consecutive_failures = 0
            except Exception:
                consecutive_failures += 1
                if consecutive_failures in (1, 10, 100):
                    # A silently-broken autoscaler looks like "tasks hang forever";
                    # log early, then rate-limit.
                    traceback.print_exc()
            self._stop.wait(self._config.poll_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


__all__ = [
    "Autoscaler",
    "AutoscalingConfig",
    "LocalNodeProvider",
    "NodeProvider",
    "request_resources",
]
