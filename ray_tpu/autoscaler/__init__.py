"""Autoscaler: declarative reconciliation of cluster size to resource demand.

Design parity: reference autoscaler v2 (`python/ray/autoscaler/v2/` — a reconciler
over an InstanceManager driven by the GCS autoscaler state, `autoscaler.py:47`
`update_autoscaling_state`) with the NodeProvider SPI of v1
(`python/ray/autoscaler/_private/node_provider.py`). The GCS exports unplaceable
demand (queued task resources + PENDING actors, `rpc_cluster_demand`); the
reconciler adds nodes until demand fits and removes nodes idle past a timeout.
`LocalNodeProvider` launches worker nodes as local processes — the
FakeMultiNodeProvider testing pattern (SURVEY.md §4.3) — while cloud providers
implement the same three methods against their APIs.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu

_REQUEST_KEY = b"autoscaler_resource_request"
_NS = "autoscaler"


# -- provider SPI ----------------------------------------------------------


class NodeProvider:
    """Three methods against your infrastructure; everything else is the reconciler."""

    def create_node(self, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Worker nodes as local raylet processes on this machine (test/laptop cloud)."""

    def __init__(self, cluster):
        self._cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self._nodes: Dict[str, Any] = {}
        self._counter = 0

    def create_node(self, resources: Dict[str, float]) -> str:
        handle = self._cluster.add_node(
            num_cpus=int(resources.get("CPU", 1)),
            resources={k: v for k, v in resources.items() if k != "CPU"},
        )
        self._counter += 1
        name = f"local-{self._counter}"
        self._nodes[name] = handle
        return name

    def terminate_node(self, node_id: str) -> None:
        handle = self._nodes.pop(node_id, None)
        if handle is not None:
            self._cluster.remove_node(handle)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)


# -- config + sdk ----------------------------------------------------------


@dataclass
class AutoscalingConfig:
    min_workers: int = 0
    max_workers: int = 4
    worker_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1})
    idle_timeout_s: float = 30.0
    poll_interval_s: float = 1.0
    upscaling_speed: int = 2  # max nodes added per reconcile round


def request_resources(*, num_cpus: Optional[float] = None,
                      bundles: Optional[List[Dict[str, float]]] = None):
    """Explicit demand hint (parity: ray.autoscaler.sdk.request_resources)."""
    import json

    demand: Dict[str, float] = {}
    if num_cpus:
        demand["CPU"] = float(num_cpus)
    for b in bundles or []:
        for r, amt in b.items():
            demand[r] = demand.get(r, 0.0) + float(amt)
    ray_tpu.global_worker().gcs_call(
        "kv_put", _NS, _REQUEST_KEY, json.dumps(demand).encode(), True
    )


# -- reconciler ------------------------------------------------------------


class Autoscaler:
    def __init__(self, provider: NodeProvider, config: Optional[AutoscalingConfig] = None):
        self._provider = provider
        self._config = config or AutoscalingConfig()
        self._idle_since: Dict[str, float] = {}  # provider node id -> first idle t
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_scale_ups = 0
        self.num_scale_downs = 0

    # -- demand/state reads ------------------------------------------------
    def _demand(self) -> Dict[str, float]:
        import json

        worker = ray_tpu.global_worker()
        out = dict(worker.gcs_call("cluster_demand")["pending"])
        raw = worker.gcs_call("kv_get", _NS, _REQUEST_KEY)
        if raw:
            requested = json.loads(raw)
            avail = worker.gcs_call("cluster_resources")["total"]
            # request_resources is a floor on TOTAL cluster resources
            for r, amt in requested.items():
                shortfall = amt - avail.get(r, 0.0)
                if shortfall > 0:
                    out[r] = out.get(r, 0.0) + shortfall
        return out

    def reconcile_once(self) -> Dict[str, int]:
        cfg = self._config
        demand = self._demand()
        nodes = self._provider.non_terminated_nodes()
        actions = {"added": 0, "removed": 0}
        # Upscale: enough worker nodes to absorb the unplaceable demand.
        if demand:
            per_node = cfg.worker_resources
            need = 0
            for r, amt in demand.items():
                cap = per_node.get(r, 0.0)
                if cap > 0:
                    need = max(need, math.ceil(amt / cap))
                elif amt > 0:
                    need = max(need, 0)  # this provider can't supply r
            room = cfg.max_workers - len(nodes)
            to_add = max(0, min(need, room, cfg.upscaling_speed))
            for _ in range(to_add):
                self._provider.create_node(dict(per_node))
                self.num_scale_ups += 1
                actions["added"] += 1
        # Downscale: provider nodes fully idle (available == total) past timeout.
        gcs_nodes = ray_tpu.global_worker().gcs_call("get_nodes")
        idle_cluster_nodes = {
            tuple(n["address"]) for n in gcs_nodes
            if n["alive"] and not n["is_head"]
            and n["resources_available"] == n["resources_total"]
            # a node with QUEUED work is not idle even though nothing is running
            # yet — terminating it would strand the queue
            and not any(n.get("pending_demand", {}).values())
        }
        now = time.monotonic()
        nodes = self._provider.non_terminated_nodes()
        removable = len(nodes) - max(cfg.min_workers, 0)
        for node_id in nodes:
            if removable <= 0:
                break
            if self._node_is_idle(node_id, idle_cluster_nodes):
                first = self._idle_since.setdefault(node_id, now)
                if now - first >= cfg.idle_timeout_s:
                    self._provider.terminate_node(node_id)
                    self._idle_since.pop(node_id, None)
                    self.num_scale_downs += 1
                    actions["removed"] += 1
                    removable -= 1
            else:
                self._idle_since.pop(node_id, None)
        return actions

    def _node_is_idle(self, provider_node_id: str, idle_cluster_nodes) -> bool:
        handle = getattr(self._provider, "_nodes", {}).get(provider_node_id)
        addr = getattr(handle, "raylet_port", None)
        if addr is None:
            return False
        return any(a[1] == addr for a in idle_cluster_nodes)

    # -- loop --------------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:
                pass
            self._stop.wait(self._config.poll_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


__all__ = [
    "Autoscaler",
    "AutoscalingConfig",
    "LocalNodeProvider",
    "NodeProvider",
    "request_resources",
]
