"""SSH cluster launcher: provision worker hosts over SSH (rsync + setup +
remote start), so a laptop can bootstrap a real multi-host pod.

Design parity: reference `python/ray/autoscaler/_private/commands.py` (`ray up`
runs NodeUpdater threads per node: rsync file mounts, run setup_commands, start
ray with the head address) over the static on-prem provider
(`python/ray/autoscaler/_private/local/node_provider.py`). Re-designed for this
runtime: hosts come from a static YAML list (TPU pods are fixed inventories,
not elastic VM fleets), provisioning is the same three phases, and the provider
plugs into the standard reconciler SPI so demand-driven scaling works over SSH
exactly like local/GCE providers.

The ssh/rsync executables are injectable (`ssh_cmd`/`rsync_cmd`) — tests drive
the full provisioning path with a fake ssh that executes locally.
"""

from __future__ import annotations

import shlex
import subprocess
from typing import Dict, List, Optional

from ray_tpu.autoscaler import NodeProvider

_SSH_OPTS = [
    "-o", "StrictHostKeyChecking=no",
    "-o", "UserKnownHostsFile=/dev/null",
    "-o", "ConnectTimeout=15",
    "-o", "LogLevel=ERROR",
]


class SSHNodeProvider(NodeProvider):
    """Static host pool provisioned over SSH.

    Config keys (from the cluster YAML `provider:` section):
      hosts:            list of worker host addresses (required)
      ssh_user:         login user (optional)
      ssh_key:          identity file (optional)
      target_dir:       remote dir file_mounts sync into (default ~/ray_tpu)
      file_mounts:      {remote_subdir_or_.: local_path} rsynced per node
      setup_commands:   list of shell commands run on the node before start
      worker_start_command: override for the node-join command; the string
                        "{address}" is substituted with the head address
      num_cpus / resources: advertised capacity per node
    """

    def __init__(self, config: dict, head_address: str,
                 ssh_cmd: Optional[List[str]] = None,
                 rsync_cmd: Optional[List[str]] = None):
        self._config = config
        self._head_address = head_address
        self._hosts: List[str] = list(config.get("hosts") or [])
        if not self._hosts:
            raise ValueError("ssh provider needs provider.hosts: [...]")
        self._ssh_cmd = ssh_cmd or config.get("ssh_cmd") or ["ssh"]
        self._rsync_cmd = rsync_cmd or config.get("rsync_cmd") or ["rsync"]
        self._active: Dict[str, str] = {}  # node id -> host
        self._counter = 0

    # -- ssh plumbing ------------------------------------------------------
    def _login(self, host: str) -> str:
        user = self._config.get("ssh_user")
        return f"{user}@{host}" if user else host

    def _ssh_base(self) -> List[str]:
        base = list(self._ssh_cmd)
        if base[0] == "ssh":
            base += _SSH_OPTS
            key = self._config.get("ssh_key")
            if key:
                base += ["-i", key]
        return base

    def run_on(self, host: str, command: str, *, check: bool = True,
               timeout: float = 300.0) -> subprocess.CompletedProcess:
        argv = self._ssh_base() + [self._login(host), command]
        return subprocess.run(
            argv, check=check, timeout=timeout, capture_output=True, text=True
        )

    def _rsync(self, host: str, local: str, remote: str):
        base = list(self._rsync_cmd)
        if base[0] == "rsync":
            ssh_transport = " ".join(
                shlex.quote(p) for p in self._ssh_base()
            )
            base += ["-az", "-e", ssh_transport]
        else:
            base += ["-az"]
        subprocess.run(
            base + [local, f"{self._login(host)}:{remote}"],
            check=True, timeout=600, capture_output=True, text=True,
        )

    # -- provisioning phases (reference: NodeUpdater.do_update) ------------
    def _provision(self, host: str):
        target = self._config.get("target_dir", "~/ray_tpu")
        self.run_on(host, f"mkdir -p {target}")
        for remote_sub, local in (self._config.get("file_mounts") or {}).items():
            dest = target if remote_sub in (".", "") else f"{target}/{remote_sub}"
            self._rsync(host, local, dest)
        for cmd in self._config.get("setup_commands") or []:
            self.run_on(host, f"cd {target} && {cmd}")
        start = self._config.get("worker_start_command")
        if start is None:
            res = []
            if self._config.get("num_cpus") is not None:
                res.append(f"--num-cpus={self._config['num_cpus']}")
            if self._config.get("resources"):
                import json

                res.append(
                    f"--resources={shlex.quote(json.dumps(self._config['resources']))}"
                )
            start = (
                "python -m ray_tpu.scripts.scripts start "
                f"--address={{address}} {' '.join(res)}"
            )
        start = start.replace("{address}", self._head_address)
        # nohup + background: the node outlives the provisioning SSH session.
        # sh -c isolation keeps redirects INSIDE the user's command working.
        self.run_on(
            host,
            f"cd {target} && nohup sh -c {shlex.quote(start)} "
            "> ray_tpu_node.log 2>&1 < /dev/null & sleep 0.1",
        )

    # -- provider SPI ------------------------------------------------------
    def create_node(self, resources: Dict[str, float]) -> str:
        free = [h for h in self._hosts if h not in self._active.values()]
        if not free:
            raise RuntimeError(
                f"ssh provider exhausted: all {len(self._hosts)} hosts active"
            )
        host = free[0]
        self._provision(host)
        self._counter += 1
        node_id = f"ssh-{self._counter}-{host}"
        self._active[node_id] = host
        return node_id

    def terminate_node(self, node_id: str) -> None:
        host = self._active.pop(node_id, None)
        if host is None:
            return
        stop = self._config.get(
            "worker_stop_command", "pkill -f ray_tpu.*raylet_main || true"
        )
        try:
            self.run_on(host, stop, check=False, timeout=60)
        except Exception:
            pass  # host unreachable: nothing to stop

    def non_terminated_nodes(self) -> List[str]:
        return list(self._active)

    def cluster_address(self, node_id: str) -> Optional[tuple]:
        host = self._active.get(node_id)
        # Port unknown (the remote raylet picks it): IP-match path in the
        # reconciler handles (host, 0).
        return (host, 0) if host else None


__all__ = ["SSHNodeProvider"]
