"""ray_tpu.dag: compiled graphs (aDAG) — pinned actor pipelines over channels.

Parity: reference `python/ray/dag/__init__.py` — InputNode, MultiOutputNode,
actor_method.bind(), DAGNode.experimental_compile(). The pipeline-parallel substrate:
steady-state execution does no task submission and no allocation, just channel
writes/reads between pinned per-actor loops.
"""

from ray_tpu.dag import collective
from ray_tpu.dag.compiled_dag import (
    CompiledDAG,
    CompiledDAGFuture,
    CompiledDAGRef,
)
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "ClassMethodNode",
    "CollectiveOutputNode",
    "CompiledDAG",
    "CompiledDAGFuture",
    "CompiledDAGRef",
    "DAGNode",
    "InputAttributeNode",
    "InputNode",
    "MultiOutputNode",
    "collective",
]
