"""CompiledDAG: pin actor loops + preallocated channels; drive with execute().

Design parity: reference `python/ray/dag/compiled_dag_node.py` (CompiledDAG :805,
ExecutableTask :478, `do_exec_tasks` actor loops :186, driver `execute()` :2546) — at
compile time every edge gets ONE mutable shared-memory channel and every actor gets a
long-running loop task that reads its inputs, runs its methods in topological order,
and writes outputs. Steady-state execution does zero task submissions and zero object
allocations — the TPU-relevant property for pipeline-parallel stage feeding.

Edges carrying array payloads (activations, logits, gradients) ride the channels'
tensor-native fast path (round 11, docs/device_channels.md): the value's array
leaves are memcpy'd into the ring slot as raw buffers behind a small pickled
skeleton — cloudpickle never serializes tensor bytes, on write OR read. Values
without qualifying arrays pickle exactly as before. Per-process frame accounting
lives in experimental.tensor_transport.transport_stats().
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.experimental.channel import Channel, ChannelClosed, RpcChannel


class _ExecSpec:
    """One actor-local step: read input channels / constants, call method (or
    reduce, for collective steps), write."""

    def __init__(self, method_name: str, arg_sources: list, kwarg_sources: dict,
                 out_channel: Optional[Channel], reduce_op: Optional[str] = None):
        self.method_name = method_name
        self.arg_sources = arg_sources      # list of ("chan", Channel)|("const", v)
        self.kwarg_sources = kwarg_sources  # name -> same
        self.out_channel = out_channel
        self.reduce_op = reduce_op          # set for collective steps


def _read_source(kind, src):
    if kind == "chan":
        return src.read()
    if kind == "pick":
        reader, key = src
        value = reader.read()
        try:
            # The channel read already happened (acks stay consistent); only the
            # projection can fail, and that failure flows through the graph.
            if isinstance(key, str) and hasattr(value, key):
                return getattr(value, key)
            return value[key]
        except Exception as e:
            return _WrappedError(e)
    return src


def _exec_loop(instance, specs: List[_ExecSpec], token: str = ""):
    """Runs inside the actor (as one pinned long-running method call)."""
    try:
        return _exec_loop_inner(instance, specs, token)
    finally:
        # Reclaim writer-side ring state of cross-node channels hosted here.
        for spec in specs:
            if spec.out_channel is not None:
                try:
                    spec.out_channel.destroy()
                except Exception:
                    pass  # teardown raced the driver's destroy of the same ring


class _OpStats:
    """Per-op read/compute/write accumulators, pushed into the task-event
    timeline periodically and at loop close (reference: compiled_dag_node.py
    op-level profiling)."""

    def __init__(self, token: str, specs: List[_ExecSpec]):
        import time

        self.token = token
        self.per_op = [
            {"read_s": 0.0, "compute_s": 0.0, "write_s": 0.0, "iters": 0}
            for _ in specs
        ]
        self._names = [s.method_name for s in specs]
        self._last_emit = time.monotonic()
        self._emitted_iters = 0

    def maybe_emit(self, force: bool = False):
        import time

        total_iters = self.per_op[0]["iters"] if self.per_op else 0
        if not force:
            if total_iters == self._emitted_iters:
                return
            if (
                total_iters - self._emitted_iters < 8
                and time.monotonic() - self._last_emit < 0.5
            ):
                return
        self._last_emit = time.monotonic()
        self._emitted_iters = total_iters
        try:
            from ray_tpu._private.worker import global_worker

            w = global_worker()
            for i, st in enumerate(self.per_op):
                w._record_event(
                    task_id=f"dagop:{self.token}:{i}",
                    name=f"dag:{self._names[i]}",
                    state="FINISHED",
                    dag_op=True,
                    **{k: round(v, 6) if isinstance(v, float) else v
                       for k, v in st.items()},
                )
        except Exception:
            pass


def _exec_loop_inner(instance, specs: List[_ExecSpec], token: str = ""):
    """Overlap-scheduled loop (reference: `python/ray/dag/dag_node_operation.py`
    reorders per-actor READ/COMPUTE/WRITE ops so channel I/O overlaps compute).

    Decomposition here: inputs NOT produced by this actor's own loop are
    prefetched by a reader thread (one iteration ahead, bounded queues), and
    all channel writes drain through a writer thread — same-actor consumers
    stay correct because ring reads block until their item exists. COMPUTE for
    iteration i therefore overlaps the reads of i+1 and the writes of i."""
    import queue as queue_mod
    import threading
    import time

    stats = _OpStats(token, specs)

    def _chan_ident(chan):
        # Reader views are distinct objects over the same segment/ring: compare
        # by transport identity, never object id.
        shm = getattr(chan, "_shm", None)
        if shm is not None:
            return ("shm", shm.name)
        return ("rpc", getattr(chan, "_name", id(chan)))

    own_outputs = {
        _chan_ident(s.out_channel) for s in specs if s.out_channel is not None
    }

    def _chan_of(kind, src):
        if kind == "chan":
            return src
        if kind == "pick":
            return src[0]
        return None

    # (spec_idx, slot) -> prefetch queue; slot is ("arg", j) | ("kw", name)
    plan: list = []
    for i, spec in enumerate(specs):
        for j, (kind, src) in enumerate(spec.arg_sources):
            chan = _chan_of(kind, src)
            if chan is not None and _chan_ident(chan) not in own_outputs:
                plan.append((i, ("arg", j), kind, src))
        for name, (kind, src) in spec.kwarg_sources.items():
            chan = _chan_of(kind, src)
            if chan is not None and _chan_ident(chan) not in own_outputs:
                plan.append((i, ("kw", name), kind, src))
    queues = {(i, slot): queue_mod.Queue(maxsize=2) for i, slot, _k, _s in plan}
    stop = threading.Event()
    reader_exc: list = []
    writer_q: queue_mod.Queue = queue_mod.Queue(maxsize=8)
    writer_exc: list = []

    def reader():
        try:
            while not stop.is_set():
                for i, slot, kind, src in plan:
                    t0 = time.monotonic()
                    v = _read_source(kind, src)
                    stats.per_op[i]["read_s"] += time.monotonic() - t0
                    queues[(i, slot)].put(v)
        except BaseException as e:  # noqa: BLE001 - surface into the main loop
            reader_exc.append(e)
            for q in queues.values():
                try:
                    q.put_nowait(_LOOP_STOP)
                except queue_mod.Full:
                    pass

    def writer():
        try:
            while True:
                item = writer_q.get()
                if item is _LOOP_STOP:
                    return
                i, chan, out = item
                t0 = time.monotonic()
                try:
                    chan.write(out)
                except ChannelClosed:
                    raise
                except Exception as e:
                    # e.g. result larger than the channel slot: report the
                    # error IN PLACE of the oversized value so the loop (and
                    # downstream consumers) stay alive and in sync.
                    chan.write(_WrappedError(e))
                stats.per_op[i]["write_s"] += time.monotonic() - t0
        except BaseException as e:  # noqa: BLE001
            writer_exc.append(e)

    threads = []
    if plan:
        threads.append(threading.Thread(target=reader, name="dag-reader", daemon=True))
    threads.append(threading.Thread(target=writer, name="dag-writer", daemon=True))
    for t in threads:
        t.start()

    def _get_input(i, slot, kind, src):
        key = (i, slot)
        q = queues.get(key)
        if q is None:
            t0 = time.monotonic()
            v = _read_source(kind, src)
            stats.per_op[i]["read_s"] += time.monotonic() - t0
            return v
        while True:
            try:
                v = q.get(timeout=0.5)
                break
            except queue_mod.Empty:
                if reader_exc:  # reader died with other queues still full
                    raise reader_exc[0]
        if v is _LOOP_STOP:
            raise reader_exc[0] if reader_exc else ChannelClosed("reader stopped")
        return v

    def _put_output(item):
        while True:
            if writer_exc:
                raise writer_exc[0]
            try:
                writer_q.put(item, timeout=0.5)
                return
            except queue_mod.Full:
                continue

    try:
        while True:
            try:
                for i, spec in enumerate(specs):
                    if writer_exc:
                        raise writer_exc[0]
                    args = [
                        _get_input(i, ("arg", j), kind, src)
                        for j, (kind, src) in enumerate(spec.arg_sources)
                    ]
                    kwargs = {
                        k: _get_input(i, ("kw", k), kind, src)
                        for k, (kind, src) in spec.kwarg_sources.items()
                    }
                    # Errors flow THROUGH the graph (as wrapped values) so one
                    # bad input poisons only its execution, not the pinned loops.
                    err = next(
                        (v for v in list(args) + list(kwargs.values())
                         if isinstance(v, _WrappedError)),
                        None,
                    )
                    if err is None:
                        t0 = time.monotonic()
                        try:
                            if spec.reduce_op is not None:
                                from ray_tpu.dag.collective import reduce_values

                                out = reduce_values(spec.reduce_op, args)
                            else:
                                out = getattr(instance, spec.method_name)(*args, **kwargs)
                        except Exception as e:  # surfaced at CompiledDAGRef.get
                            out = _WrappedError(e)
                        stats.per_op[i]["compute_s"] += time.monotonic() - t0
                    else:
                        out = err
                    stats.per_op[i]["iters"] += 1
                    if spec.out_channel is not None:
                        _put_output((i, spec.out_channel, out))
                stats.maybe_emit()
            except ChannelClosed:
                return "closed"
    finally:
        stop.set()
        # Drop queued (stale) writes, then a guaranteed stop slot: on an error
        # exit the writer must not keep pushing desynchronized results into
        # live downstream channels, nor block forever on an empty queue.
        while True:
            try:
                writer_q.get_nowait()
            except queue_mod.Empty:
                break
        writer_q.put(_LOOP_STOP)
        # Unblock a reader parked on a full queue so it can observe closed
        # channels and exit (its channels are being torn down by the driver).
        for q in queues.values():
            try:
                q.get_nowait()
            except queue_mod.Empty:
                pass
        stats.maybe_emit(force=True)


_LOOP_STOP = object()


class CompiledDAGRef:
    """The driver-side result future of one execute() call.

    A ref dropped without get() does NOT strand its ring slot: __del__ (or an
    explicit release()) marks the index abandoned and the DAG consumes the
    value lazily — the reference CompiledDAGRef likewise consumes unread
    results in its destructor so fire-and-forget drivers can't wedge the
    graph with RayCgraphCapacityExceeded."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._value: Any = None
        self._ready = False
        self._released = False

    def get(self, timeout: Optional[float] = 60):
        if self._released:
            raise ValueError("this CompiledDAGRef was released")
        if not self._ready:
            self._dag._resolve_until(self._idx, timeout)
            with self._dag._state_lock:
                consume = not self._ready
                if consume:
                    self._value = self._dag._pending.pop(self._idx)
                    self._ready = True
            if consume:
                self._dag._note_consumed(self._idx)
        if isinstance(self._value, _WrappedError):
            raise self._value.error
        return self._value

    def release(self):
        """Give up on this result: its capacity slot is reclaimed (lazily, at
        the next capacity-bound submit) and get() becomes an error."""
        if self._ready or self._released:
            self._released = True
            return
        self._released = True
        self._dag._abandon(self._idx)

    def __del__(self):
        try:
            if not self._dag._torn_down:
                self.release()
        except Exception:
            pass  # interpreter teardown: the DAG is going away anyway


class CompiledDAGFuture:
    """Awaitable result of one execute_async() call (reference:
    compiled_dag_node.py CompiledDAGFuture :2627). Channel reads run in a
    thread-pool executor so an asyncio Serve replica can drive a compiled DAG
    without blocking its event loop."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._value: Any = None
        self._ready = False
        self._released = False

    def __await__(self):
        return self.get_async().__await__()

    async def get_async(self, timeout: Optional[float] = 60):
        if self._released:
            raise ValueError("this CompiledDAGFuture was released")
        if not self._ready:
            await self._dag._resolve_until_async(self._idx, timeout)
            # Another coroutine awaiting this SAME future may have consumed it
            # while we were suspended; the state lock also covers sync gets.
            with self._dag._state_lock:
                consume = not self._ready
                if consume:
                    self._value = self._dag._pending.pop(self._idx)
                    self._ready = True
            if consume:
                self._dag._note_consumed(self._idx)
        if isinstance(self._value, _WrappedError):
            raise self._value.error
        return self._value

    def release(self):
        """Non-async mirror of CompiledDAGRef.release (safe from __del__)."""
        if self._ready or self._released:
            self._released = True
            return
        self._released = True
        self._dag._abandon(self._idx)

    def __del__(self):
        try:
            if not self._dag._torn_down:
                self.release()
        except Exception:
            pass  # interpreter teardown: the DAG is going away anyway


class _WrappedError:
    def __init__(self, error):
        self.error = error


class CompiledDAG:
    def __init__(self, leaf: DAGNode, *, buffer_size_bytes: Optional[int] = None,
                 max_inflight_executions: Optional[int] = None,
                 _timeout_s: Optional[float] = None):
        import threading
        import uuid as _uuid

        from ray_tpu._private.config import CONFIG

        if buffer_size_bytes is None:
            buffer_size_bytes = CONFIG.dag_buffer_size_bytes
        if max_inflight_executions is None:
            max_inflight_executions = CONFIG.dag_max_inflight_executions
        if _timeout_s is None:
            _timeout_s = CONFIG.dag_execute_timeout_s
        self._buffer = buffer_size_bytes
        self._timeout = _timeout_s
        self._torn_down = False
        self._token = _uuid.uuid4().hex[:12]  # op-profile event namespace
        self._exec_count = 0
        self._pending: Dict[int, Any] = {}
        # In-flight pipelining (reference compiled_dag_node.py:837): channels
        # get max_inflight_executions ring slots so that many executions can
        # genuinely be in flight; execute() raises RayCgraphCapacityExceeded
        # past the bound instead of deadlocking on a full ring.
        self._max_inflight = max(1, int(max_inflight_executions))
        # Reference parity: num_shm_buffers = max_inflight_executions
        # (compiled_dag_node.py:961) — the ring can hold every in-flight value,
        # so a driver that respects the bound never wedges a writer.
        self._num_slots = max(2, self._max_inflight)
        self._consumed_rounds = 0  # rounds with EVERY output consumed by get()
        self._consumed: Dict[int, int] = {}  # round -> outputs consumed so far
        # Output indices whose refs were dropped/released unread: their values
        # are consumed lazily (stream order) so abandoned refs free capacity
        # instead of wedging the ring.
        self._abandoned: set = set()
        # Input channel is single-writer: concurrent execute/execute_async
        # submissions must serialize their capacity-check + ring write or two
        # writers race the same slot and a round is silently lost.
        self._submit_lock = threading.Lock()
        # Consumption bookkeeping (capacity accounting + pending pops) shared
        # by sync gets and async futures.
        self._state_lock = threading.Lock()
        self._build(leaf)
        # Per-output-reader progress: how many rounds each has consumed. Kept per
        # reader so a timeout on one output can't shift another reader's stream.
        self._reader_round = [0] * self._num_outputs
        self._stream_locks = [threading.Lock() for _ in range(self._num_outputs)]

    # -- compilation -------------------------------------------------------
    def _build(self, leaf: DAGNode):
        nodes = leaf._all_nodes()
        input_nodes = [n for n in nodes if isinstance(n, InputNode)]
        if len(input_nodes) != 1:
            raise ValueError(f"a compiled DAG needs exactly one InputNode, "
                             f"found {len(input_nodes)}")
        self._input_node = input_nodes[0]
        if isinstance(leaf, MultiOutputNode):
            outputs = leaf.outputs
        else:
            outputs = [leaf]
        self._num_outputs = len(outputs)
        for out in outputs:
            if not isinstance(out, (ClassMethodNode, CollectiveOutputNode)):
                raise ValueError(
                    "DAG outputs must be actor method or collective nodes"
                )

        # Consumer counts per node, counted per ARG OCCURRENCE (a node passed twice
        # to one bind() needs two reader slots — source_for allocates one per
        # occurrence, and every slot must have its own ack word). A collective
        # step consumes EVERY participant's output (peers read each other's
        # producer channels and reduce locally).
        consumers: Dict[int, int] = {}
        for n in nodes:
            if isinstance(n, CollectiveOutputNode):
                for p in n.participants:
                    consumers[id(p)] = consumers.get(id(p), 0) + 1
            elif isinstance(n, ClassMethodNode):
                for u in n.upstream:
                    consumers[id(u)] = consumers.get(id(u), 0) + 1
        # Edge placement: shm channels only connect processes on ONE node;
        # edges that cross nodes get an RpcChannel (ring in the writer process,
        # readers pull over the direct worker servers). Reference: cross-node
        # mutable-plasma channels, experimental_mutable_object_provider.h:143.
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        driver_node = w.node_id
        node_cache: Dict[Any, Any] = {}

        def node_of(actor):
            aid = actor._actor_id
            if aid not in node_cache:
                info = w.gcs_call("wait_actor_alive", aid, 60.0)
                addr = (info or {}).get("address") or {}
                node_cache[aid] = addr.get("node_id")
            return node_cache[aid]

        consumer_nodes: Dict[int, set] = {}
        for n in nodes:
            if isinstance(n, CollectiveOutputNode):
                for p in n.participants:
                    consumer_nodes.setdefault(id(p), set()).add(node_of(n.actor))
            elif isinstance(n, ClassMethodNode):
                for u in n.upstream:
                    consumer_nodes.setdefault(id(u), set()).add(node_of(n.actor))
        for out in outputs:
            consumer_nodes.setdefault(id(out), set()).add(driver_node)

        def make_channel(writer_node, reader_nodes, n_readers, owner):
            if all(rn == writer_node for rn in reader_nodes):
                return Channel(self._buffer, n_readers,
                               num_slots=self._num_slots)
            if owner is None:
                raise RuntimeError(
                    "compiled DAGs with cross-node edges need a local data "
                    "plane: this driver has no direct server (thin-client "
                    "mode), so actors on other nodes cannot pull its channels"
                )
            return RpcChannel(self._buffer, n_readers, num_slots=self._num_slots,
                              owner=owner)

        # Input channel read by every arg occurrence that consumes the input
        # (directly or through attribute nodes).
        input_consumers = consumers.get(id(self._input_node), 0) + sum(
            consumers.get(id(n), 0)
            for n in nodes
            if isinstance(n, InputAttributeNode)
        )
        input_reader_nodes = set()
        for n in nodes:
            if isinstance(n, (InputNode, InputAttributeNode)):
                input_reader_nodes |= consumer_nodes.get(id(n), set())
        direct_server = getattr(w, "_direct_server", None)
        driver_addr = (
            ("addr", (getattr(w, "node_ip", "127.0.0.1"), direct_server.port))
            if direct_server is not None else None
        )
        self._input_channel = make_channel(
            driver_node, input_reader_nodes, max(1, input_consumers), driver_addr
        )
        for out in outputs:
            consumers[id(out)] = consumers.get(id(out), 0) + 1  # driver reads leaves

        # Create one output channel per producer node that anyone consumes.
        chan_of: Dict[int, Channel] = {}
        for n in nodes:
            if (
                isinstance(n, (ClassMethodNode, CollectiveOutputNode))
                and consumers.get(id(n), 0) > 0
            ):
                chan_of[id(n)] = make_channel(
                    node_of(n.actor), consumer_nodes.get(id(n), set()),
                    consumers[id(n)], ("actor", n.actor._actor_id),
                )

        # Assign reader slots.
        next_slot: Dict[int, int] = {}
        input_next_slot = [0]

        def source_for(value) -> tuple:
            if isinstance(value, InputNode):
                slot = input_next_slot[0]
                input_next_slot[0] += 1
                return ("chan", self._input_channel.reader(slot))
            if isinstance(value, InputAttributeNode):
                slot = input_next_slot[0]
                input_next_slot[0] += 1
                return ("pick", (self._input_channel.reader(slot), value.key))
            if isinstance(value, (ClassMethodNode, CollectiveOutputNode)):
                ch = chan_of[id(value)]
                slot = next_slot.get(id(value), 0)
                next_slot[id(value)] = slot + 1
                return ("chan", ch.reader(slot))
            return ("const", value)

        # Group method nodes per actor, topological order (nodes already topo-sorted
        # by _all_nodes' postorder).
        per_actor: Dict[Any, List[_ExecSpec]] = {}
        actor_of: Dict[Any, Any] = {}
        for n in nodes:
            if isinstance(n, CollectiveOutputNode):
                specs = per_actor.setdefault(n.actor._actor_id, [])
                actor_of[n.actor._actor_id] = n.actor
                # Fixed participant order on every actor: deterministic reduce.
                arg_sources = [source_for(p) for p in n.participants]
                specs.append(
                    _ExecSpec(None, arg_sources, {}, chan_of.get(id(n)),
                              reduce_op=n.op)
                )
                continue
            if not isinstance(n, ClassMethodNode):
                continue
            specs = per_actor.setdefault(n.actor._actor_id, [])
            actor_of[n.actor._actor_id] = n.actor
            arg_sources = [source_for(a) for a in n.args]
            kwarg_sources = {k: source_for(v) for k, v in n.kwargs.items()}
            specs.append(
                _ExecSpec(n.method_name, arg_sources, kwarg_sources,
                          chan_of.get(id(n)))
            )
        # Driver-side output readers (last reader slot of each output's channel).
        self._output_readers: List[Channel] = []
        for out in outputs:
            ch = chan_of[id(out)]
            slot = next_slot.get(id(out), 0)
            next_slot[id(out)] = slot + 1
            self._output_readers.append(ch.reader(slot))

        self._channels = [self._input_channel] + list(chan_of.values())
        self._loop_refs = []
        self._actors = list(actor_of.values())
        from ray_tpu.actor import ActorMethod

        for a_idx, (actor_id, specs) in enumerate(per_actor.items()):
            actor = actor_of[actor_id]
            # Pin the loop: one long-running call per actor via the generic
            # apply hook (the reference's __ray_call__ + do_exec_tasks pattern).
            # Per-actor token suffix: spec indices are per-actor, so profile
            # event ids must not collide across actors.
            self._loop_refs.append(
                ActorMethod(actor, "__rtpu_apply__").remote(
                    _exec_loop, specs, f"{self._token}:a{a_idx}"
                )
            )

    # -- execution ---------------------------------------------------------
    def _check_capacity(self):
        if self._torn_down:
            raise RuntimeError("this compiled DAG was torn down")
        if self._exec_count - self._consumed_rounds >= self._max_inflight:
            from ray_tpu.exceptions import RayCgraphCapacityExceeded

            raise RayCgraphCapacityExceeded(
                f"{self._exec_count - self._consumed_rounds} executions in "
                f"flight >= max_inflight_executions="
                f"{self._max_inflight}: get()/await results before "
                "submitting more"
            )

    def _note_consumed(self, idx: int):
        with self._state_lock:
            rnd = idx // self._num_outputs
            n = self._consumed.get(rnd, 0) + 1
            if n >= self._num_outputs:
                self._consumed.pop(rnd, None)
                self._consumed_rounds += 1
            else:
                self._consumed[rnd] = n

    def _abandon(self, idx: int):
        """A ref for `idx` was dropped/released unread. If its value already
        sits in _pending (a later get() on the same stream read past it),
        consume it now; otherwise remember the index for a lazy drain."""
        with self._state_lock:
            if self._torn_down:
                return
            if idx in self._pending:
                self._pending.pop(idx)
                claimed = True
            else:
                self._abandoned.add(idx)
                claimed = False
        if claimed:
            self._note_consumed(idx)

    def _store_round(self, j: int, value):
        """Record the value just read for output stream j's current round —
        or drop it on the floor if its ref was abandoned. Caller holds
        stream lock j."""
        with self._state_lock:
            idx = self._reader_round[j] * self._num_outputs + j
            self._reader_round[j] += 1
            abandoned = idx in self._abandoned
            if abandoned:
                self._abandoned.discard(idx)
            else:
                self._pending[idx] = value
        if abandoned:
            self._note_consumed(idx)

    def _drain_abandoned(self):
        """Consume abandoned results that are next in their stream (channel
        reads are strictly ordered per reader, so only stream-heads can be
        drained; the rest unblock as earlier rounds are read)."""
        while True:
            with self._state_lock:
                heads = [
                    (idx, divmod(idx, self._num_outputs))
                    for idx in sorted(self._abandoned)
                ]
                heads = [
                    (idx, rnd, j) for idx, (rnd, j) in heads
                    if self._reader_round[j] == rnd
                ]
            if not heads:
                return
            for idx, rnd, j in heads:
                with self._stream_locks[j]:
                    with self._state_lock:
                        runnable = (
                            idx in self._abandoned
                            and self._reader_round[j] == rnd
                        )
                    if runnable:
                        value = self._output_readers[j].read(self._timeout)
                        self._store_round(j, value)

    def _submit(self, input_value) -> int:
        """Capacity check + count + single-writer ring write, atomically."""
        with self._submit_lock:
            if self._exec_count - self._consumed_rounds >= self._max_inflight:
                # At the bound: reclaim capacity from refs that were dropped
                # unread before failing the submit.
                self._drain_abandoned()
            self._check_capacity()
            idx = self._exec_count
            self._exec_count += 1
            self._input_channel.write(input_value, timeout=self._timeout)
            return idx

    def execute(self, input_value: Any = None) -> List[CompiledDAGRef] | CompiledDAGRef:
        idx = self._submit(input_value)
        refs = [CompiledDAGRef(self, idx * self._num_outputs + k)
                for k in range(self._num_outputs)]
        return refs if self._num_outputs > 1 else refs[0]

    async def execute_async(
        self, input_value: Any = None
    ) -> List[CompiledDAGFuture] | CompiledDAGFuture:
        """Submit without blocking the event loop; returns awaitable futures
        (reference compiled_dag_node.py execute_async :2627). Up to
        max_inflight_executions submissions can overlap; results may be
        awaited out of submission order (per-output streams stay ordered)."""
        import asyncio

        # The submit (capacity check + ring write) runs in the executor: the
        # write blocks only while a slow consumer drains, and the submit lock
        # serializes concurrent submissions off the event loop.
        idx = await asyncio.get_running_loop().run_in_executor(
            None, self._submit, input_value
        )
        futs = [CompiledDAGFuture(self, idx * self._num_outputs + k)
                for k in range(self._num_outputs)]
        return futs if self._num_outputs > 1 else futs[0]

    def _resolve_until(self, target_idx: int, timeout: Optional[float]):
        round_needed, j = divmod(target_idx, self._num_outputs)
        reader = self._output_readers[j]
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._reader_round[j] <= round_needed:
            # Per-STREAM lock: readers of output j serialize with each other
            # (sync gets and async futures alike) without head-of-line
            # blocking reads of other outputs whose values may already be
            # sitting in their channels.
            with self._stream_locks[j]:
                if self._reader_round[j] > round_needed:
                    break
                remaining = None if deadline is None else deadline - time.monotonic()
                value = reader.read(remaining)
                self._store_round(j, value)

    async def _resolve_until_async(self, target_idx: int,
                                   timeout: Optional[float]):
        """Async mirror of _resolve_until: the blocking channel read runs in
        the default executor, serialized per output stream."""
        import asyncio

        loop = asyncio.get_running_loop()
        round_needed, j = divmod(target_idx, self._num_outputs)
        reader = self._output_readers[j]
        deadline = None if timeout is None else time.monotonic() + timeout

        def read_one():
            # Lock is taken in the worker thread: sync gets contend fairly.
            with self._stream_locks[j]:
                if self._reader_round[j] > round_needed:
                    return
                remaining = None if deadline is None else deadline - time.monotonic()
                value = reader.read(remaining)
                self._store_round(j, value)

        while self._reader_round[j] <= round_needed:
            await loop.run_in_executor(None, read_one)

    def __getattr__(self, name):
        raise AttributeError(name)

    def op_profile(self) -> dict:
        """Latest per-op timing (read/compute/write seconds + iterations),
        keyed by op label. Sourced from the task-event timeline, which the
        pinned loops feed periodically and at teardown (reference:
        compiled_dag_node.py op-level profiling)."""
        from ray_tpu._private.worker import global_worker

        prefix = f"dagop:{self._token}:"
        events = global_worker().gcs_call("list_dag_op_events", prefix)
        out: dict = {}
        for e in events:
            tid = str(e.get("task_id", ""))
            out[f"{tid[len(prefix):]}:{e.get('name')}"] = {
                k: e[k] for k in ("read_s", "compute_s", "write_s", "iters")
                if k in e
            }
        return out

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        # Close EVERY channel: a loop can be blocked in a downstream write (full
        # ring), not just an upstream read — both sides observe the closed flag.
        for ch in self._channels:
            ch.close()
        try:
            ray_tpu.get(self._loop_refs, timeout=10)
        except Exception:
            pass
        for ch in self._channels:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def interpret(leaf: DAGNode, *args) -> Any:
    """Uncompiled execution with plain actor calls (DAGNode.execute parity)."""
    input_value = args[0] if args else None
    cache: Dict[int, Any] = {}

    def run(n: DAGNode):
        if id(n) in cache:
            return cache[id(n)]
        if isinstance(n, InputNode):
            out = input_value
        elif isinstance(n, InputAttributeNode):
            parent = run(n.upstream[0])
            out = parent[n.key] if not isinstance(n.key, str) or not hasattr(
                parent, n.key
            ) else getattr(parent, n.key)
        elif isinstance(n, ClassMethodNode):
            call_args = [run(a) if isinstance(a, DAGNode) else a for a in n.args]
            call_kwargs = {
                k: run(v) if isinstance(v, DAGNode) else v for k, v in n.kwargs.items()
            }
            method = getattr(n.actor, n.method_name)
            out = ray_tpu.get(method.remote(*call_args, **call_kwargs))
        elif isinstance(n, CollectiveOutputNode):
            from ray_tpu.dag.collective import reduce_values

            out = reduce_values(n.op, [run(p) for p in n.participants])
        elif isinstance(n, MultiOutputNode):
            out = [run(o) for o in n.outputs]
        else:
            raise TypeError(f"unknown node {type(n).__name__}")
        cache[id(n)] = out
        return out

    return run(leaf)
