"""Collective operations as compiled-graph nodes.

Design parity: reference `python/ray/dag/collective_node.py` +
`ray.experimental.collective.allreduce.bind(tensor_nodes)` — an allreduce whose
participants are DAG nodes on different actors, executed inside the compiled
graph's pinned loops. TPU-first note: IN-GRAPH device collectives belong inside
jitted SPMD programs (XLA inserts them over ICI); this DAG-level collective is the
host/CPU-tensor analog riding the shared-memory channels.
"""

from __future__ import annotations

import itertools
from typing import List

from ray_tpu.dag.dag_node import ClassMethodNode, CollectiveOutputNode

_group_counter = itertools.count(1)

REDUCE_OPS = ("sum", "mean", "max", "min")


class _AllReduce:
    def bind(self, nodes: List[ClassMethodNode], op: str = "sum") -> List[CollectiveOutputNode]:
        """Bind an allreduce over the outputs of `nodes` (one per actor).
        Returns one CollectiveOutputNode per participant, in the same order."""
        if op not in REDUCE_OPS:
            raise ValueError(f"unsupported reduce op {op!r}; one of {REDUCE_OPS}")
        if len(nodes) < 2:
            raise ValueError("allreduce needs at least two participants")
        if not all(isinstance(n, ClassMethodNode) for n in nodes):
            raise ValueError("allreduce participants must be actor method nodes")
        actors = {n.actor._actor_id for n in nodes}  # ActorID hashes by value
        if len(actors) != len(nodes):
            raise ValueError("allreduce participants must live on distinct actors")
        gid = next(_group_counter)
        return [
            CollectiveOutputNode(nodes, i, op, gid) for i in range(len(nodes))
        ]


allreduce = _AllReduce()


def reduce_values(op: str, values: list):
    """Host-side reduction over numpy/jax arrays or scalars."""
    import numpy as np

    arrays = [np.asarray(v) for v in values]
    if op == "sum":
        out = arrays[0]
        for a in arrays[1:]:
            out = out + a  # rebinding allocates; inputs never mutated
        return out
    if op == "mean":
        return reduce_values("sum", arrays) / len(arrays)
    if op == "max":
        out = arrays[0]
        for a in arrays[1:]:
            out = np.maximum(out, a)
        return out
    out = arrays[0]
    for a in arrays[1:]:
        out = np.minimum(out, a)
    return out
