"""DAG node types for compiled graphs.

Design parity: reference `python/ray/dag/` — InputNode (`input_node.py`),
ClassMethodNode (`class_node.py` — created by actor_method.bind()),
MultiOutputNode (`output_node.py`), and `experimental_compile`
(`dag_node.py:278`). A DAG is built with .bind() calls, then compiled into
pinned per-actor execution loops over shared-memory channels.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class DAGNode:
    def __init__(self, upstream: List["DAGNode"]):
        self.upstream = upstream

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)

    def execute(self, *args):
        """Uncompiled (interpreted) execution — parity with DAGNode.execute:
        walks the graph with plain actor calls. Useful for debugging."""
        from ray_tpu.dag.compiled_dag import interpret

        return interpret(self, *args)

    def _all_nodes(self) -> List["DAGNode"]:
        seen: list = []

        def visit(n):
            if any(n is s for s in seen):
                return
            for u in n.upstream:
                visit(u)
            seen.append(n)

        visit(self)
        return seen


class InputNode(DAGNode):
    """The driver-provided input. Supports `with InputNode() as inp:` and
    `inp[i]` / `inp.key` access (InputAttributeNode)."""

    def __init__(self):
        super().__init__([])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key):
        return InputAttributeNode(self, key)

    def __getattr__(self, name):
        if name.startswith("_") or name in ("upstream",):
            raise AttributeError(name)
        return InputAttributeNode(self, name)


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key):
        super().__init__([parent])
        self.key = key


class ClassMethodNode(DAGNode):
    """One bound actor-method call in the graph."""

    def __init__(self, actor, method_name: str, args: tuple, kwargs: dict):
        upstream = [a for a in args if isinstance(a, DAGNode)] + [
            v for v in kwargs.values() if isinstance(v, DAGNode)
        ]
        super().__init__(upstream)
        self.actor = actor
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(list(outputs))
        self.outputs = list(outputs)


class CollectiveOutputNode(DAGNode):
    """Participant i's view of an in-graph collective (reference:
    python/ray/dag/collective_node.py). Produced by `collective.allreduce.bind`:
    each participant's actor reads every peer's contribution channel and reduces
    locally, so the collective is part of the pinned exec loops — no extra task
    submissions per round."""

    def __init__(self, participants: List[ClassMethodNode], index: int, op: str,
                 group_id: int):
        # Upstream = ALL participants: the reduce consumes every contribution.
        super().__init__(list(participants))
        self.participants = list(participants)
        self.index = index
        self.op = op
        self.group_id = group_id
        self.actor = participants[index].actor
