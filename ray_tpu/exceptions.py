"""Public exception hierarchy.

Design parity: reference `python/ray/exceptions.py` (RayError, RayTaskError, RayActorError,
GetTimeoutError, ObjectLostError, OutOfMemoryError, ...).
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base for all framework errors."""


class RayTpuTaskError(RayTpuError):
    """A task raised an exception on the executing worker.

    Mirrors the reference's RayTaskError: wraps the remote traceback and re-raises at
    `get()` time on the caller, preserving the original exception as `.cause`.
    """

    def __init__(self, function_name: str, tb_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = tb_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{tb_str}")

    def __reduce__(self):
        return (RayTpuTaskError, (self.function_name, self.traceback_str, self.cause))

    @classmethod
    def from_exception(cls, function_name: str, exc: Exception) -> "RayTpuTaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        picklable = exc
        try:  # exceptions holding unpicklable state fall back to a string repr
            import cloudpickle

            cloudpickle.dumps(exc)
        except Exception:
            picklable = None
        return cls(function_name, tb, picklable)

    def as_instanceof_cause(self):
        """Return an exception that is also an instance of the cause's type.

        The cause may itself be a (wrapped) task error when the failure
        crossed several actor hops — e.g. engine -> DP replica -> DP router
        -> driver: walk to the innermost non-task-error cause so a typed
        error (UnknownAdapterError, EngineOverloadedError, ...) stays
        catchable by type no matter how many hops it rode."""
        cause = self.cause
        while isinstance(cause, RayTpuTaskError):
            cause = cause.cause
        if cause is None:
            return self

        class _Wrapped(RayTpuTaskError, type(cause)):
            def __init__(self, outer):
                RayTpuTaskError.__init__(
                    self, outer.function_name, outer.traceback_str, outer.cause
                )

            def __str__(self):
                return RayTpuTaskError.__str__(self)

            def __reduce__(self):
                return (_rebuild_task_error, (self.function_name, self.traceback_str, self.cause))

        try:
            return _Wrapped(self)
        except Exception:
            return self


def _rebuild_task_error(function_name, tb_str, cause):
    return RayTpuTaskError(function_name, tb_str, cause).as_instanceof_cause()


class RayTpuActorError(RayTpuError):
    """The actor died before or during method execution.

    Carries a structured death cause (reference: ActorDeathCause in
    src/ray/protobuf/common.proto) — exit code / signal and the tail of the dead
    worker's log — in the message so `get()` on a dead actor's call explains itself.
    """

    def __init__(self, actor_id=None, msg: str = "actor died"):
        self.actor_id = actor_id
        super().__init__(msg)

    def __reduce__(self):
        # Default Exception pickling would call cls(msg), shifting the message
        # into the actor_id slot and silently resetting msg to "actor died".
        return (type(self), (self.actor_id, self.args[0] if self.args else "actor died"))


class ActorDiedError(RayTpuActorError):
    pass


class ActorUnavailableError(RayTpuActorError):
    """Actor temporarily unreachable (restarting); call may be retried."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    def __init__(self, object_id=None, msg: str | None = None):
        self.object_id = object_id
        super().__init__(msg or f"object {object_id} lost and could not be reconstructed")

    def __reduce__(self):
        # Same pitfall as RayTpuActorError: keep object_id out of the msg slot.
        return (type(self), (self.object_id, self.args[0] if self.args else None))


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} was cancelled")

    def __reduce__(self):
        # Default Exception pickling would call cls(formatted_message),
        # shifting the message into the task_id slot after a .remote() hop.
        return (type(self), (self.task_id,))


class PendingCallsLimitExceeded(RayTpuError):
    pass


class TaskUnschedulableError(RayTpuError):
    pass


class RayCgraphCapacityExceeded(RayTpuError):
    """A compiled DAG has max_inflight_executions results outstanding; the
    caller must consume (get/await) results before submitting more
    (reference: ray.exceptions.RayCgraphCapacityExceeded)."""
