"""ray_tpu: a TPU-native distributed AI framework.

Tasks, actors, and a shared-memory distributed object store with ownership-based
reference counting (the Ray core model, rebuilt), plus TPU-first AI libraries: SPMD
training over JAX/pjit/shard_map meshes, collectives over ICI/DCN via XLA, Pallas kernels
for long-context attention, datasets, serving, tuning, and RL.

Public API parity: reference `python/ray/__init__.py` — init/shutdown, remote, get, put,
wait, kill, get_actor, cluster_resources, nodes.
"""

from __future__ import annotations

import atexit
import os
from typing import Any, Optional

from ray_tpu import exceptions  # noqa: F401
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID  # noqa: F401
from ray_tpu._private.object_ref import ObjectRef  # noqa: F401
from ray_tpu._private.worker import (
    CoreWorker,
    ObjectRefGenerator,
    global_worker,
    global_worker_or_none,
    set_global_worker,
)
from ray_tpu.actor import (  # noqa: F401
    ActorClass,
    ActorHandle,
    exit_actor,
    get_actor,
    kill,
    method,
)
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"

_driver_state: dict[str, Any] = {}


def _current_namespace() -> str:
    return _driver_state.get("namespace", "")


def is_initialized() -> bool:
    return global_worker_or_none() is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[dict] = None,
    labels: Optional[dict] = None,
    namespace: str = "",
    object_store_memory: int = 0,
    ignore_reinit_error: bool = False,
    worker_env: Optional[dict] = None,
    _system_config: Optional[dict] = None,
    _raylet_port: Optional[int] = None,
):
    """Start (or connect to) a cluster and attach this process as the driver.

    Parity: reference `ray.init` (python/ray/_private/worker.py:1427). address=None starts
    a head node locally; address="host:gcs_port" or the RAY_TPU_ADDRESS env var connects to
    an existing cluster through a raylet on this machine; address="ray_tpu://host:gcs_port"
    attaches as a THIN CLIENT with no local daemons — the data plane rides RPC to the head
    node's raylet (reference: Ray Client, ray:// in util/client/).
    """
    if is_initialized():
        if ignore_reinit_error:
            return _driver_state.get("context")
        raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")

    from ray_tpu._private import node as node_mod

    address = address or os.environ.get("RAY_TPU_ADDRESS")
    _driver_state["namespace"] = namespace

    if address in (None, "local"):
        session_dir = node_mod.make_session_dir()
        total = dict(resources or {})
        if "CPU" not in total:
            total["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
        if "memory" not in total:
            # Schedulable memory (bytes): host RAM minus the object-store share
            # (reference: ray auto-advertises `memory` the same way).
            try:
                import psutil

                from ray_tpu._private.config import CONFIG as _CFG

                total["memory"] = float(int(
                    psutil.virtual_memory().total
                    * (1.0 - _CFG.object_store_memory_fraction)
                ))
            except Exception:
                pass
        from ray_tpu.accelerators import detect_accelerator_resources

        for r, amt in detect_accelerator_resources(num_tpus).items():
            total.setdefault(r, amt)
        head = node_mod.start_node(
            head=True,
            gcs_addr=None,
            resources=total,
            labels=labels,
            session_dir=session_dir,
            object_store_bytes=object_store_memory,
            worker_env=worker_env,
        )
        _driver_state["head"] = head
        _driver_state["session_dir"] = session_dir
        gcs_addr = head.gcs_addrs  # every candidate under a replicated GCS
        raylet_addr = ("127.0.0.1", head.raylet_port)
        from ray_tpu._private import usage_stats

        usage_stats.start_session(session_dir, {"resources": total})
    elif address.startswith(("ray_tpu://", "ray_tpu+proxy://")):
        # Thin client: discover the head raylet via the GCS; no local daemons.
        # ray_tpu+proxy:// tunnels EVERY dial through a ClientProxy
        # (util/client/proxier.py; reference: Ray Client's proxier) — the
        # client only ever reaches the proxy's single public port.
        via = None
        if address.startswith("ray_tpu+proxy://"):
            rest = address[len("ray_tpu+proxy://"):]
            token = None
            if "@" in rest:  # ray_tpu+proxy://<token>@host:port
                token, rest = rest.split("@", 1)
            host, port = rest.split(":")
            via = (host, int(port), os.urandom(8).hex(), token)
            gcs_addr = ("gcs", 0)  # symbolic: the proxy substitutes its GCS
        else:
            from ray_tpu._private.gcs_replication import parse_addrs

            gcs_addr = parse_addrs(address[len("ray_tpu://"):])
        from ray_tpu._private import rpc as _rpclib
        from ray_tpu._private.gcs_replication import parse_addrs as _parse

        async def _head_raylet():
            # Walk the candidate list: under a replicated GCS only the
            # primary answers client RPCs; followers redirect (NotPrimary).
            last_err: Exception | None = None
            for addr in _parse(gcs_addr):
                conn = await _rpclib.connect(*addr, name="client-probe", via=via)
                try:
                    nodes = await conn.call("get_nodes")
                except _rpclib.NotPrimaryError as e:
                    last_err = e
                    continue
                finally:
                    await conn.close()
                alive = [n for n in nodes if n["alive"]]
                heads = [n for n in alive if n.get("is_head")] or alive
                if not heads:
                    raise RuntimeError(f"no alive nodes behind {address}")
                return tuple(heads[0]["address"])
            raise RuntimeError(
                f"no GCS primary behind {address}: {last_err}")

        # Probe on a private IO thread: init() must work from inside a running
        # event loop (notebooks/async apps are the thin client's home turf).
        probe_loop = _rpclib.IoLoop(name="client-probe")
        try:
            raylet_addr = probe_loop.run(_head_raylet(), 30)
        finally:
            probe_loop.stop()
        from ray_tpu._private import usage_stats as _usage

        _usage.start_session(_client_usage_dir(), {"mode": "thin-client"})
        worker = CoreWorker(
            mode="driver", raylet_addr=raylet_addr, gcs_addr=gcs_addr,
            remote_data_plane=True, proxy=via,
        )
        set_global_worker(worker)
        worker.connect()
        _driver_state["worker"] = worker
        atexit.register(_atexit_shutdown)
        ctx = RuntimeContext(worker)
        _driver_state["context"] = ctx
        return ctx
    else:
        from ray_tpu._private.gcs_replication import parse_addrs

        gcs_addr = parse_addrs(address)  # "h:p" or "h:p,h:p,..." candidates
        from ray_tpu._private import usage_stats as _usage

        _usage.start_session(_client_usage_dir(), {"mode": "connect"})
        raylet_port = _raylet_port or os.environ.get("RAY_TPU_RAYLET_PORT")
        if raylet_port is None:
            raise RuntimeError(
                "connecting to an existing cluster requires RAY_TPU_RAYLET_PORT "
                "(the local raylet's port)"
            )
        raylet_addr = ("127.0.0.1", int(raylet_port))

    worker = CoreWorker(mode="driver", raylet_addr=raylet_addr, gcs_addr=gcs_addr)
    set_global_worker(worker)
    worker.connect()
    _driver_state["worker"] = worker
    atexit.register(_atexit_shutdown)
    ctx = RuntimeContext(worker)
    _driver_state["context"] = ctx
    return ctx


def _client_usage_dir() -> str:
    """Per-driver usage dir for drivers that did not start the head node."""
    import tempfile

    d = os.path.join(tempfile.gettempdir(), "ray_tpu", f"usage_{os.getpid()}")
    os.makedirs(d, exist_ok=True)
    _driver_state.setdefault("session_dir", d)
    return d


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:
        pass


def shutdown():
    worker = global_worker_or_none()
    if worker is not None:
        worker.disconnect()
        set_global_worker(None)
    head = _driver_state.pop("head", None)
    if head is not None:
        head.terminate()
    _driver_state.pop("worker", None)
    _driver_state.pop("context", None)


def remote(*args, **kwargs):
    """Decorator: turn a function into a RemoteFunction or a class into an ActorClass."""

    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return wrap(args[0])
    if args:
        raise TypeError("@ray_tpu.remote() accepts only keyword options")
    return wrap


def get(refs, timeout: Optional[float] = None):
    worker = global_worker()
    if isinstance(refs, ObjectRef):
        return worker.get([refs], timeout)[0]
    refs = list(refs) if not isinstance(refs, list) else refs
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(
                f"ray_tpu.get() expects an ObjectRef or a list of ObjectRefs, got {type(r).__name__}"
            )
    return worker.get(refs, timeout)


def put(value: Any) -> ObjectRef:
    return global_worker().put(value)


def wait(refs, *, num_returns: int = 1, timeout: Optional[float] = None, fetch_local=True):
    return global_worker().wait(refs, num_returns, timeout, fetch_local)


def cluster_resources() -> dict:
    return global_worker().gcs_call("cluster_resources")["total"]


def available_resources() -> dict:
    return global_worker().gcs_call("cluster_resources")["available"]


def nodes() -> list:
    return global_worker().gcs_call("get_nodes")


def timeline(filename: Optional[str] = None) -> list:
    """Task events; with `filename`, also write a Chrome trace (chrome://tracing /
    perfetto) — parity: `ray timeline` (python/ray/_private/internal_api.py)."""
    events = global_worker().gcs_call("list_task_events", 100000)
    if filename:
        import json

        # Pair RUNNING/FINISHED-or-FAILED into complete ("X") slices per task.
        starts: dict = {}
        trace = []
        for e in events:
            tid = e.get("task_id")
            state = e.get("state")
            if state == "RUNNING":
                starts[tid] = e
            elif state in ("FINISHED", "FAILED") and tid in starts:
                s = starts.pop(tid)
                trace.append({
                    "name": e.get("name", "task"),
                    "cat": "task",
                    "ph": "X",
                    "ts": s["time"] * 1e6,
                    "dur": max(0.0, (e["time"] - s["time"]) * 1e6),
                    "pid": e.get("worker_id", "worker")[:8] if isinstance(
                        e.get("worker_id"), str) else "worker",
                    "tid": tid[:8],
                    "args": {"state": state},
                })
        with open(filename, "w") as f:
            json.dump(trace, f)
    return events


class RuntimeContext:
    """Parity: ray.get_runtime_context()."""

    def __init__(self, worker: CoreWorker):
        self._worker = worker

    def get_node_id(self):
        return self._worker.node_id

    def get_worker_id(self):
        return self._worker.worker_id

    def get_job_id(self):
        return self._worker.job_id

    def get_actor_id(self):
        return self._worker.actor_id

    def get_task_id(self):
        return self._worker.current_task_id

    @property
    def namespace(self) -> str:
        return _current_namespace()


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(global_worker())


__all__ = [
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "RemoteFunction",
    "RuntimeContext",
    "available_resources",
    "cluster_resources",
    "exceptions",
    "exit_actor",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "timeline",
    "wait",
]
